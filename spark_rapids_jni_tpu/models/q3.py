"""NDS q3: star join (store_sales x item x date_dim) + grouped aggregation.

    select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price)
    from date_dim, store_sales, item
    where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
      and i_manufact_id = M and d_moy = 11
    group by d_year, i_brand_id, i_brand
    order by d_year, sum_agg desc, i_brand_id

Third query pattern in the models family (q97 = shuffle join-count, q5 =
broadcast rollup): a selective dimension FILTER pushed through two dense
dimension joins into one grouped money aggregation.  TPU shape: both
dimensions are dense surrogate-keyed, so each join is a replicated-table
gather; the group key (d_year, i_brand_id) lives in a small dense product
space, so the aggregation is one masked segment-sum into a
[n_years * n_brands] grid and the distributed form psums that grid over
the data axis — no row exchange, same as q5's partials.

Money stays unscaled int64 cents (decimal scale 2) end to end; brand
STRINGS materialize only in the host-formatted result rows.

Since round 6 the int64 path is ONE compiled plan (:func:`q3_plan`,
plans/ir.py): both gathers, the filter and the grouped segment-sum trace
into a single jitted program cached on (plan structure, dtype signature,
pow2 batch bucket), and the governed runner admits the whole plan as one
working set (SplitAndRetryOOM re-executes the fused program on fact
halves — exact, sums/counts are additive).  The pre-plan eager per-op
path survives as :func:`q3_local_unfused`, the bit-parity oracle
tests/test_plans.py pins the fused program against.  The decimal-columns
variant keeps its own fused step (Column pytrees are outside the scalar
plan IR).
"""

from __future__ import annotations

from typing import List, NamedTuple

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu.models.tpcds import Q3Data
from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, shard_map
from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.ir import Bin, Cast, band_all, col, lit

__all__ = ["Q3Row", "q3_local", "q3_local_unfused", "q3_plan",
           "make_distributed_q3", "run_distributed_q3",
           "run_distributed_q3_columns", "q3_columns_host_oracle",
           "q3_working_set_bytes"]


class Q3Row(NamedTuple):
    d_year: int
    brand_id: int
    brand: str
    sum_agg: int  # cents


class _Partials(NamedTuple):
    sums: jnp.ndarray  # [n_years * n_brands] int64 cents
    counts: jnp.ndarray  # [n_years * n_brands] int32


def _partials(ss_item, ss_item_v, ss_date, ss_date_v, price,
              item_brand, item_manufact, date_year, date_moy,
              *, n_brands: int, year0: int, n_years: int,
              date_sk0: int, manufact_id: int, moy: int) -> _Partials:
    """Device body over [rows] facts; dims are replicated dense tables."""
    i_idx = jnp.clip(ss_item - 1, 0, item_brand.shape[0] - 1)
    d_idx = jnp.clip(ss_date - date_sk0, 0, date_year.shape[0] - 1)
    ok = (
        ss_item_v & ss_date_v
        & (item_manufact[i_idx] == manufact_id)
        & (date_moy[d_idx] == moy)
    )
    brand = item_brand[i_idx].astype(jnp.int32)  # 1-based
    year_off = (date_year[d_idx] - year0).astype(jnp.int32)
    group = jnp.clip(year_off, 0, n_years - 1) * n_brands + (brand - 1)
    ngroups = n_years * n_brands
    # analyze: ignore[governed-allocation] - per-op ORACLE path: since the
    # plan port this body runs only eagerly under q3_local_unfused, the
    # bit-parity reference the fused (governed) program is checked against
    # in tests; group-grid partials are tiny and test-scoped by design
    sums = jnp.zeros((ngroups,), jnp.int64).at[group].add(
        jnp.where(ok, price, 0), mode="drop")
    # analyze: ignore[governed-allocation] - same oracle-path rationale
    counts = jnp.zeros((ngroups,), jnp.int32).at[group].add(
        jnp.where(ok, 1, 0), mode="drop")
    return _Partials(sums, counts)


def _assemble_rows(counts: np.ndarray, sum_of, year0: int, n_brands: int,
                   render_brands) -> List[Q3Row]:
    """Shared result assembly: drop empty groups, decode the group grid
    (year = year0 + g//n_brands, brand = g%n_brands + 1), attach brand
    names via ``render_brands(zero_based_idx_array)``, order by
    (d_year, sum desc, brand_id) — ONE owner of the grid layout and sort
    rule for both the int64 and the decimal-columns variants."""
    groups = np.nonzero(counts)[0]
    names = render_brands((groups % n_brands).astype(np.int32))
    rows = [
        Q3Row(year0 + int(g) // n_brands, int(g) % n_brands + 1,
              name, sum_of(int(g)))
        for g, name in zip(groups, names)
    ]
    rows.sort(key=lambda r: (r.d_year, -r.sum_agg, r.brand_id))
    return rows


def _format(parts: _Partials, data: Q3Data, year0: int) -> List[Q3Row]:
    """Host: int64-partials formatting (host-list brand lookup)."""
    sums = np.asarray(parts.sums)
    return _assemble_rows(
        np.asarray(parts.counts), lambda g: int(sums[g]), year0,
        len(data.brand_names),
        lambda idx: [data.brand_names[i] for i in idx])


def _geometry(data: Q3Data):
    year0 = int(data.date_year.min())
    n_years = int(data.date_year.max()) - year0 + 1
    return dict(
        n_brands=len(data.brand_names), year0=year0, n_years=n_years,
        date_sk0=int(data.date_sk[0]), manufact_id=data.manufact_id,
        moy=data.moy,
    )


def _facts(data: Q3Data) -> dict:
    return dict(
        ss_item=data.ss_item_sk, ss_item_v=data.ss_item_sk_valid,
        ss_date=data.ss_sold_date_sk, ss_date_v=data.ss_sold_date_sk_valid,
        price=data.ss_ext_sales_price,
    )


# ------------------------------------------------------------------ the plan


@functools.lru_cache(maxsize=64)
def q3_plan(*, n_brands: int, year0: int, n_years: int, date_sk0: int,
            manufact_id: int, moy: int) -> ir.Plan:
    """The whole q3 device pipeline as ONE plan: scan -> item gather ->
    date gather -> manufact/moy filter -> grouped segment-sum into the
    dense [n_years * n_brands] grid.  Geometry scalars normalize through
    ``plans.ir.lit`` so equal geometry always builds an EQUAL plan (one
    cache entry on the process-global plan cache).  Memoized per
    geometry: the per-request hot path must not rebuild (and re-hash)
    the plan tree every call."""
    item = ir.Dim("item", ("brand", "manufact"))
    date = ir.Dim("date_dim", ("year", "moy"))
    node: ir.Node = ir.Scan(
        "store_sales", ("ss_item", "ss_item_v", "ss_date", "ss_date_v",
                        "price"))
    node = ir.GatherJoin(node, item, key=col("ss_item"), base=lit(1),
                         fields=(("brand", "brand"),
                                 ("manufact", "manufact")))
    node = ir.GatherJoin(node, date, key=col("ss_date"), base=lit(date_sk0),
                         fields=(("year", "year"), ("moy", "moy")))
    node = ir.Filter(node, band_all(
        col("ss_item_v"), col("ss_date_v"),
        Bin("eq", col("manufact"), lit(manufact_id)),
        Bin("eq", col("moy"), lit(moy)),
    ))
    # group = clip(year - year0, 0, n_years-1) * n_brands + (brand - 1),
    # exactly the per-op body's grid arithmetic (brand is 1-based)
    year_off = Cast(Bin("sub", col("year"), lit(year0)), "int32")
    clipped = Bin("min", Bin("max", year_off, lit(0)), lit(n_years - 1))
    group = Bin("add", Bin("mul", clipped, lit(n_brands)),
                Bin("sub", Cast(col("brand"), "int32"), lit(1)))
    node = ir.Project(node, (("group", group),))
    sink = ir.SegmentAgg(
        node, key=col("group"), num_segments=n_years * n_brands,
        aggs=(("sums", col("price"), "int64"),
              ("counts", lit(1), "int32")))
    return ir.Plan("q3", (sink,))


def _q3_tables(facts: dict, dims: dict) -> dict:
    """The plan's input tables from the fact/dim array dicts."""
    return {
        "store_sales": dict(facts),
        "item": {"brand": dims["item_brand"],
                 "manufact": dims["item_manufact"]},
        "date_dim": {"year": dims["date_year"], "moy": dims["date_moy"]},
    }


def _dims(data: Q3Data) -> dict:
    # raw numpy: q3_local's jnp ops take them directly, and
    # run_distributed_q3 device_puts them with a replicated sharding
    # (no device->host->device round-trip)
    return dict(
        item_brand=data.item_brand_id,
        item_manufact=data.item_manufact_id,
        date_year=data.date_year,
        date_moy=data.date_moy,
    )


def q3_local_unfused(data: Q3Data) -> List[Q3Row]:
    """Per-op eager q3 (the pre-plan shape): one device dispatch per op.
    The plan path's bit-parity oracle."""
    geo = _geometry(data)
    parts = _partials(
        *(jnp.asarray(v) for v in _facts(data).values()),
        **{k: jnp.asarray(v) for k, v in _dims(data).items()}, **geo)
    return _format(parts, data, geo["year0"])


def q3_local(data: Q3Data) -> List[Q3Row]:
    """Single-chip q3 through the compiled plan: gathers, filter and
    grouped sum are ONE jitted program (cached across calls on the pow2
    bucket lattice), then host formatting."""
    from spark_rapids_jni_tpu.plans.runtime import execute_plan

    geo = _geometry(data)
    plan = q3_plan(**geo)
    outputs = execute_plan(None, plan, _q3_tables(_facts(data), _dims(data)))
    return _format(_Partials(outputs["sums"], outputs["counts"]),
                   data, geo["year0"])


def make_distributed_q3(mesh, data: Q3Data):
    """Compiled distributed q3 plan over ``mesh``'s data axis.

    Returns the :class:`plans.cache.CompiledPlan` for ``data``'s geometry
    and batch bucket — facts sharded over DATA_AXIS, dims replicated,
    the group grid psum'd.  Same-geometry data returns the IDENTICAL
    cached object (plan-cache identity, replacing the per-module lru
    step cache) with O(1) host work on a hit — the key derives from
    lengths and dtypes, never a padded dataset copy."""
    from spark_rapids_jni_tpu.plans.runtime import compiled_plan_for

    plan = q3_plan(**_geometry(data))
    return compiled_plan_for(plan, mesh, _q3_tables(_facts(data),
                                                    _dims(data)))


def _pad_facts(facts: dict, dp: int) -> dict:
    """dp-aligned pow2-quantized padding (bounded compile variants);
    pad rows carry False validity."""
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    n = len(facts["ss_item"])
    pad = quantized_rows(n, dp) - n
    if pad == 0:
        return facts
    out = {k: np.concatenate([v, np.zeros(pad, v.dtype)])
           for k, v in facts.items()}
    out["ss_item_v"][-pad:] = False
    out["ss_date_v"][-pad:] = False
    return out


def q3_working_set_bytes(facts_or_data, dp: int = 1) -> int:
    """Reserved bytes for one governed q3 attempt over the given facts
    (inputs + masks/buckets + partials headroom): the admission size for
    the decimal-columns runner, and what tests size budgets from.  The
    plan-compiled runner admits via ``plans.runtime
    .plan_working_set_bytes``, which applies the SAME quantized-bytes x3
    margin to the plan's scan tables — numerically equal here, pinned by
    test_plans.test_q3_admission_formulas_agree so budget-sizing tests
    can't silently desynchronize from the runner's real admission.  With
    ``dp``, row counts are the quantized (padded) lengths run() actually
    uploads."""
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    facts = (facts_or_data if isinstance(facts_or_data, dict)
             else _facts(facts_or_data))
    return sum(quantized_rows(len(v), dp) * v.itemsize
               for v in facts.values()) * 3


def _split_facts(facts: dict):
    n = len(facts["ss_item"])
    return [{k: v[:n // 2] for k, v in facts.items()},
            {k: v[n // 2:] for k, v in facts.items()}]


def run_distributed_q3(mesh, data: Q3Data, *, budget=None, task_id: int = 0,
                       manage_task: bool = True) -> List[Q3Row]:
    """Governed distributed q3 through the compiled plan: ONE admission
    for the fused working set, RetryOOM re-runs the fused program,
    SplitAndRetryOOM halves fact rows and re-executes the fused program
    per half (exact: sums/counts are additive), one flight-recorder task
    spans the plan."""
    from spark_rapids_jni_tpu.plans.runtime import run_governed_plan

    geo = _geometry(data)
    plan = q3_plan(**geo)
    outputs = run_governed_plan(
        mesh, plan, _q3_tables(_facts(data), _dims(data)),
        budget=budget, task_id=task_id, manage_task=manage_task,
    )
    return _format(_Partials(outputs["sums"], outputs["counts"]),
                   data, geo["year0"])


# ----------------------------------------------------------- columns variant
# The real TPC-DS q3 selects i_brand (a STRING) and sums a DECIMAL money
# column.  This variant puts both through the flagship governed distributed
# path: ss_ext_sales_price flows as a Decimal128Column whose per-group SUM
# is accumulated in 128-bit limb arithmetic on device — exact mod 2^128,
# i.e. for every total that fits int128 (reference decimal_utils.cu:32
# chunked math; here the unsigned low limb is decomposed into 32-bit-safe
# segment sums recombined after the psum, while the top limb accumulates
# with ordinary wrapping int64 adds, which ARE mod-2^64 adds and therefore
# modularly correct for the high limb at any magnitude).  The brand
# dimension is a device StringColumn whose result rows are RENDERED through
# the string machinery (padded gather + strings_from_padded), not a host
# list lookup.


class _DecPartials(NamedTuple):
    hi: jnp.ndarray  # int64[n_groups] high limb of the decimal sum
    lo: jnp.ndarray  # uint64[n_groups] low limb
    counts: jnp.ndarray  # int32[n_groups]


def _dec_partials(ss_item, ss_date, price, item_brand, item_manufact,
                  date_year, date_moy, *, n_brands: int, year0: int,
                  n_years: int, date_sk0: int, manufact_id: int,
                  moy: int) -> _DecPartials:
    """Device body: 128-bit grouped money sum over nullable Columns.

    The low limb is decomposed into 32-bit halves so its carries are
    recoverable (segment sums stay int64-exact for any batch under 2^31
    rows); halves recombine into (hi, lo) AFTER the cross-device psum
    (the psum is linear in the decomposed sums).  The HIGH limb needs no
    decomposition: it is the top limb, so a wrapping int64 accumulation
    is exactly the required mod-2^64 arithmetic — intermediate wraps
    cannot corrupt a total that fits int128.
    """
    i_idx = jnp.clip(ss_item.data - 1, 0, item_brand.shape[0] - 1)
    d_idx = jnp.clip(ss_date.data - date_sk0, 0, date_year.shape[0] - 1)
    ok = (
        ss_item.is_valid() & ss_date.is_valid() & price.is_valid()
        & (item_manufact[i_idx] == manufact_id)
        & (date_moy[d_idx] == moy)
    )
    brand = item_brand[i_idx].astype(jnp.int32)
    year_off = (date_year[d_idx] - year0).astype(jnp.int32)
    group = jnp.clip(year_off, 0, n_years - 1) * n_brands + (brand - 1)
    ngroups = n_years * n_brands

    lo0 = (price.lo & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
    lo1 = (price.lo >> jnp.uint64(32)).astype(jnp.int64)

    def seg(values, dtype=jnp.int64):
        return jnp.zeros((ngroups,), dtype).at[group].add(
            jnp.where(ok, values, 0), mode="drop")

    s0 = jax.lax.psum(seg(lo0), (DATA_AXIS,))
    s1 = jax.lax.psum(seg(lo1), (DATA_AXIS,))
    sh = jax.lax.psum(seg(price.hi), (DATA_AXIS,))
    counts = jax.lax.psum(seg(1, jnp.int32), (DATA_AXIS,))

    # recombine: total = sh*2^64 + s1*2^32 + s0 (mod 2^128), s0/s1 >= 0
    u = s1 + (s0 >> 32)
    lo = ((u.astype(jnp.uint64) & jnp.uint64(0xFFFFFFFF))
          << jnp.uint64(32)) | (s0.astype(jnp.uint64)
                                & jnp.uint64(0xFFFFFFFF))
    hi = sh + (u >> 32)
    return _DecPartials(hi, lo, counts)


@functools.lru_cache(maxsize=32)
def _q3_columns_step_cached(mesh, geo_items: tuple):
    from spark_rapids_jni_tpu.obs.seam import COMPILE, seam

    geo = dict(geo_items)
    with seam(COMPILE, "q3_columns_step"):
        def body(ss_item, ss_date, price, item_brand, item_manufact,
                 date_year, date_moy):
            return _dec_partials(ss_item, ss_date, price, item_brand,
                                 item_manufact, date_year, date_moy, **geo)

        step = shard_map(
            body, mesh=mesh,
            in_specs=(P(DATA_AXIS),) * 3 + (P(),) * 4,
            out_specs=_DecPartials(P(), P(), P()),
            check_vma=False,
        )
        return jax.jit(step)


def _price_limbs(price: np.ndarray):
    """int64 cents -> two's-complement (hi, lo) 64-bit limb arrays."""
    lo = price.astype(np.int64).view(np.uint64)
    hi = np.where(price < 0, np.int64(-1), np.int64(0))
    return hi, lo


def q3_columns_host_oracle(data: Q3Data) -> List[Q3Row]:
    """Arbitrary-precision host oracle (python ints — exact at magnitudes
    where the int64 oracle in q3_local would overflow)."""
    geo = _geometry(data)
    sums: dict = {}
    counts: dict = {}
    for i in range(len(data.ss_item_sk)):
        if not (data.ss_item_sk_valid[i] and data.ss_sold_date_sk_valid[i]):
            continue
        isk = int(data.ss_item_sk[i])
        dsk = int(data.ss_sold_date_sk[i]) - geo["date_sk0"]
        if not (1 <= isk <= len(data.item_sk)) or \
                not (0 <= dsk < len(data.date_year)):
            continue
        if int(data.item_manufact_id[isk - 1]) != geo["manufact_id"]:
            continue
        if int(data.date_moy[dsk]) != geo["moy"]:
            continue
        key = (int(data.date_year[dsk]), int(data.item_brand_id[isk - 1]))
        sums[key] = sums.get(key, 0) + int(data.ss_ext_sales_price[i])
        counts[key] = counts.get(key, 0) + 1
    rows = [Q3Row(y, b, data.brand_names[b - 1], s)
            for (y, b), s in sums.items()]
    rows.sort(key=lambda r: (r.d_year, -r.sum_agg, r.brand_id))
    return rows


def run_distributed_q3_columns(mesh, data: Q3Data, *, budget=None,
                               task_id: int = 0,
                               manage_task: bool = True) -> List[Q3Row]:
    """Governed distributed q3 with Decimal128Column money and a
    StringColumn brand dimension.

    Same protocol as :func:`run_distributed_q3` (admission, RetryOOM,
    row-split SplitAndRetryOOM) but per-group sums are exact for every
    total that fits int128 — far beyond the int64 path's range (128-bit
    limbs on device; combine in python ints) — and the result brand
    strings are gathered from the device StringColumn via the padded-view
    machinery.
    """
    import contextlib

    from spark_rapids_jni_tpu.columnar.column import (
        Column,
        Decimal128Column,
        strings_column,
        strings_from_padded,
    )
    from spark_rapids_jni_tpu.columnar.dtypes import INT32, decimal
    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )

    from jax.sharding import NamedSharding

    geo = _geometry(data)
    dp = mesh.shape[DATA_AXIS]
    step = _q3_columns_step_cached(mesh, tuple(sorted(geo.items())))
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    # analyze: ignore[governed-allocation] - shared replicated dim tables,
    # as in run_distributed_q3 above
    dims = {k: jax.device_put(v, rep) for k, v in _dims(data).items()}
    brands = strings_column(data.brand_names)  # the STRING dimension

    hi0, lo0 = _price_limbs(data.ss_ext_sales_price)
    facts = dict(
        ss_item=data.ss_item_sk, ss_item_v=data.ss_item_sk_valid,
        ss_date=data.ss_sold_date_sk, ss_date_v=data.ss_sold_date_sk_valid,
        price_hi=hi0, price_lo=lo0,
    )

    def nbytes_of(f):
        return q3_working_set_bytes(f, dp)

    def run(f):
        from spark_rapids_jni_tpu.obs.seam import COLLECTIVE, TRANSFER, seam

        padded = _pad_facts(f, dp)
        with seam(TRANSFER, "q3_columns_batch_upload"):
            put = lambda v: jax.device_put(  # noqa: E731
                np.ascontiguousarray(v), sharding)
            ss_item = Column(put(padded["ss_item"]),
                             put(padded["ss_item_v"]), INT32)
            ss_date = Column(put(padded["ss_date"]),
                             put(padded["ss_date_v"]), INT32)
            price = Decimal128Column(
                put(padded["price_hi"]), put(padded["price_lo"]),
                None, decimal(38, 2))
        with seam(COLLECTIVE, "launch:q3_columns_step"):
            out = step(ss_item, ss_date, price, *dims.values())
            jax.block_until_ready(out)
        hi = np.asarray(out.hi)
        lo = np.asarray(out.lo)
        sums = [int(h) * (1 << 64) + int(x)
                for h, x in zip(hi.astype(np.int64), lo.astype(np.uint64))]
        return sums, np.asarray(out.counts)

    def combine(results):
        sums = [sum(r[0][g] for r in results)
                for g in range(len(results[0][0]))]
        counts = sum(r[1] for r in results)
        return sums, counts

    budget = budget if budget is not None else default_device_budget()
    ctx = (task_context(budget.gov, task_id) if manage_task
           else contextlib.nullcontext())
    with ctx:
        sums, counts = run_with_split_retry(
            budget, facts, nbytes_of=nbytes_of, run=run,
            split=_split_facts, combine=combine)

    # result assembly shares _assemble_rows; brand strings are RENDERED
    # from the device StringColumn.  The gather length is pow2-quantized
    # (pad rows gather row 0, sliced off after) so a long-lived executor
    # sees a bounded shape-variant set, not one cached executable per
    # distinct non-empty-group count.
    from spark_rapids_jni_tpu.columnar.column import next_pow2

    def render_brands(idx: np.ndarray):
        n_sel = len(idx)
        sel_np = np.zeros(next_pow2(max(n_sel, 1)), np.int32)
        sel_np[:n_sel] = idx
        padded, lens = brands.padded()
        sel = jnp.asarray(sel_np)
        return strings_from_padded(
            padded[sel], lens[sel]).to_list()[:n_sel]

    return _assemble_rows(counts, lambda g: sums[g], geo["year0"],
                          len(data.brand_names), render_brands)
