"""Mini NDS q97: a distributed two-table join-count over the device mesh.

BASELINE.md staged config 5 is "NDS TPC-DS q5+q97 end-to-end"; this module is
the framework-native q97 core.  TPC-DS q97 counts (customer_sk, item_sk)
pairs sold in store only, catalog only, and both, from two fact tables —
i.e. a full outer join on a composite key reduced to presence counts:

    SELECT SUM(store_only), SUM(catalog_only), SUM(both) FROM
      (SELECT customer_sk, item_sk FROM store_sales GROUP BY 1,2) ss
      FULL OUTER JOIN
      (SELECT customer_sk, item_sk FROM catalog_sales GROUP BY 1,2) cs
      USING (customer_sk, item_sk)

Distributed plan (the Spark plan's TPU-native shape):

1. hash the composite key per row (Spark murmur3 row hashing, ops/hashing);
2. all_to_all shuffle BOTH tables by ``hash % ndev`` over the data axis —
   co-locating every distinct key on one owner shard (the exchange Spark
   does with its UCX shuffle, here one ICI collective);
3. per shard: sort the union of (key, source-tag) pairs and count
   equal-key runs by which sources appear — a static-shape sort-merge
   "join" (XLA-friendly: no dynamic hash table);
4. psum the three counters over the mesh.

Shuffled row counts are data-dependent; capacity is a static bound with
overflow reported (parallel/shuffle.py) — the caller retries with a larger
capacity exactly like a Spark shuffle spill retry.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, axis_size, shard_map
from spark_rapids_jni_tpu.parallel.shuffle import all_to_all_shuffle, partition_of
from spark_rapids_jni_tpu.plans import ir as ir_mod


class Q97Out(NamedTuple):
    store_only: jnp.ndarray  # scalar int32
    catalog_only: jnp.ndarray
    both: jnp.ndarray
    dropped: jnp.ndarray  # shuffle capacity overflows (0 == exact result)


def _composite_key(customer_sk: jnp.ndarray, item_sk: jnp.ndarray) -> jnp.ndarray:
    """One int64 key per (customer, item) pair.

    Both sks are positive 32-bit surrogate keys in TPC-DS, so packing is
    exact (no collisions), unlike hashing the pair.
    """
    return (customer_sk.astype(jnp.int64) << 32) | (
        item_sk.astype(jnp.int64) & 0xFFFFFFFF
    )


def _count_runs(keys: jnp.ndarray, is_store: jnp.ndarray, valid: jnp.ndarray):
    """Sort-merge presence counting over one shard's co-located rows.

    For every distinct valid key: did it appear with a store tag, a catalog
    tag, or both?  Returns (store_only, catalog_only, both) scalars.
    """
    # order by key; invalid rows sort last via the max sentinel
    sentinel = jnp.int64(0x7FFFFFFFFFFFFFFF)
    k = jnp.where(valid, keys, sentinel)
    order = jnp.argsort(k)
    ks = k[order]
    store_s = jnp.where(valid, is_store, False)[order]
    cat_s = jnp.where(valid, ~is_store, False)[order]

    # run starts: first element or key change
    n = ks.shape[0]
    prev = jnp.concatenate([ks[:1] - 1, ks[:-1]])
    run_start = ks != prev
    run_id = jnp.cumsum(run_start.astype(jnp.int32)) - 1

    # per-run presence via segment max (bounded by n runs)
    has_store = jax.ops.segment_max(
        store_s.astype(jnp.int32), run_id, num_segments=n
    )
    has_cat = jax.ops.segment_max(
        cat_s.astype(jnp.int32), run_id, num_segments=n
    )
    run_valid = jax.ops.segment_max(
        (ks != sentinel).astype(jnp.int32), run_id, num_segments=n
    )
    has_store = has_store * run_valid
    has_cat = has_cat * run_valid
    both = jnp.sum((has_store & has_cat).astype(jnp.int32))
    store_only = jnp.sum((has_store & (1 - has_cat)).astype(jnp.int32))
    cat_only = jnp.sum((has_cat & (1 - has_store)).astype(jnp.int32))
    return store_only, cat_only, both


def q97_host_oracle(store, catalog):
    """(store_only, catalog_only, both) via host sets — the reference
    semantics both the NDS harness and the monte-carlo workload verify
    against (non-null keys)."""
    s = set(zip(store[0].tolist(), store[1].tolist()))
    c = set(zip(catalog[0].tolist(), catalog[1].tolist()))
    return len(s - c), len(c - s), len(s & c)


def q97_local(store: tuple, catalog: tuple) -> Q97Out:
    """Single-chip q97 core over (customer_sk, item_sk) int arrays."""
    sk = _composite_key(*store)
    ck = _composite_key(*catalog)
    keys = jnp.concatenate([sk, ck])
    is_store = jnp.concatenate(
        # analyze: ignore[governed-allocation] - the single-chip unfused
        # oracle the parity tests pin the plan path against: tag/validity
        # masks are O(input) bools beside already-resident key arrays, and
        # callers (tests, dryrun) run it whole, never under the retry ladder
        [jnp.ones(sk.shape, bool), jnp.zeros(ck.shape, bool)]
    )
    # analyze: ignore[governed-allocation] - same oracle-path mask
    so, co, b = _count_runs(keys, is_store, jnp.ones(keys.shape, bool))
    return Q97Out(so, co, b, jnp.int32(0))


def _sharded_q97(s_cust, s_item, c_cust, c_item, capacity: int,
                 s_valid=None, c_valid=None):
    dp = axis_size(DATA_AXIS)
    sk = _composite_key(s_cust, s_item)
    ck = _composite_key(c_cust, c_item)

    # co-locate keys from BOTH tables with ONE tagged all_to_all: same bytes
    # moved, half the collective launches on the query hot path
    keys = jnp.concatenate([sk, ck])
    tag = jnp.concatenate(
        [jnp.ones(sk.shape, jnp.int8), jnp.zeros(ck.shape, jnp.int8)]
    )
    row_valid = None
    if s_valid is not None or c_valid is not None:
        sv = jnp.ones(sk.shape, bool) if s_valid is None else s_valid
        cv = jnp.ones(ck.shape, bool) if c_valid is None else c_valid
        row_valid = jnp.concatenate([sv, cv])
    part = partition_of(keys, dp)
    ex = all_to_all_shuffle(
        {"k": keys, "tag": tag}, part, capacity, axis=DATA_AXIS,
        row_valid=row_valid,
    )
    so, co, b = _count_runs(
        ex.columns["k"], ex.columns["tag"] == 1, ex.valid
    )
    axes = (DATA_AXIS,)
    return Q97Out(
        jax.lax.psum(so, axes),
        jax.lax.psum(co, axes),
        jax.lax.psum(b, axes),
        jax.lax.psum(ex.dropped, axes),
    )


@functools.lru_cache(maxsize=64)
def q97_plan(capacity: int) -> ir_mod.Plan:
    """The whole distributed q97 pipeline as ONE plan: two fact scans
    project the packed composite key, union with a source tag, exchange
    by key hash (static ``capacity`` is plan structure — one compiled
    variant per pow2 capacity, as the lru step cache kept before), then
    sort-merge presence counting.  Mesh-only (contains an Exchange)."""
    from spark_rapids_jni_tpu.plans.ir import Bin, Cast, col, lit

    key = Bin("bor",
              Bin("shl", Cast(col("cust"), "int64"), lit(32)),
              Bin("band", Cast(col("item"), "int64"), lit(0xFFFFFFFF)))
    store = ir_mod.Project(ir_mod.Scan("store", ("cust", "item")),
                           (("key", key),))
    catalog = ir_mod.Project(ir_mod.Scan("catalog", ("cust", "item")),
                             (("key", key),))
    node = ir_mod.Union((store, catalog), tag="tag", tag_values=(1, 0))
    node = ir_mod.Exchange(node, key=col("key"), capacity=int(capacity),
                           fields=("key", "tag"))
    return ir_mod.Plan("q97", (ir_mod.PresenceCount(node, key="key",
                                                    tag="tag"),))


def make_distributed_q97(mesh, capacity: int, with_validity: bool = False):
    """jit-compiled distributed q97 over ``mesh``'s data axis.

    Inputs: four [rows] int arrays sharded over DATA_AXIS (store customer/
    item, catalog customer/item); with ``with_validity``, two more bool
    arrays (store row-valid, catalog row-valid) marking padding rows that
    must not count.  ``capacity`` bounds per-destination shuffle buckets
    over the COMBINED row stream (both tables ride one tagged all_to_all);
    Q97Out.dropped > 0 means retry with a larger one.
    """
    if with_validity:
        def body(s_cust, s_item, c_cust, c_item, s_valid, c_valid):
            return _sharded_q97(s_cust, s_item, c_cust, c_item, capacity,
                                s_valid=s_valid, c_valid=c_valid)

        in_specs = tuple(P(DATA_AXIS) for _ in range(6))
    else:
        body = functools.partial(_sharded_q97, capacity=capacity)
        in_specs = tuple(P(DATA_AXIS) for _ in range(4))
    step = shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=Q97Out(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(step)


# ------------------------------------------------------- nullable columns --
# q97 over real Column inputs with per-column null validity.  SQL semantics:
# NULL keys group *within* a side (DISTINCT treats NULLs as one group) but
# never join *across* sides (NULL = NULL is unknown), so a side's null-key
# groups count as that side's "only" rows.

_PAIR_SENTINEL = jnp.int64(0x7FFFFFFFFFFFFFFF)


def _pair_key(cust, cust_valid, item, item_valid, side: int):
    """(k_hi, k_lo) 2-limb group key over nullable (cust, item) int32 pairs.

    Each component widens to 33 bits (value | null flag); rows with any
    null key additionally carry a null marker + the side bit in k_lo so
    null groups stay side-local (never equal across tables).
    """
    # null slots must not leak their underlying data bits into the group key
    # (invalid data is garbage by contract): normalize them to 0|nullflag
    c_ext = jnp.where(cust_valid, cust.astype(jnp.int64) & 0xFFFFFFFF,
                      jnp.int64(1) << 32)
    i_ext = jnp.where(item_valid, item.astype(jnp.int64) & 0xFFFFFFFF,
                      jnp.int64(1) << 32)
    null_any = (~cust_valid) | (~item_valid)
    marker = jnp.int64((2 | (side & 1)) << 33)
    k_lo = i_ext | jnp.where(null_any, marker, jnp.int64(0))
    return c_ext, k_lo


def _count_runs_pair(k_hi, k_lo, is_store, valid):
    """_count_runs generalized to a 2-limb key (lexsorted)."""
    kh = jnp.where(valid, k_hi, _PAIR_SENTINEL)
    kl = jnp.where(valid, k_lo, _PAIR_SENTINEL)
    order = jnp.lexsort((kl, kh))
    khs = kh[order]
    kls = kl[order]
    store_s = jnp.where(valid, is_store, False)[order]
    cat_s = jnp.where(valid, ~is_store, False)[order]

    n = khs.shape[0]
    prev_hi = jnp.concatenate([khs[:1] - 1, khs[:-1]])
    prev_lo = jnp.concatenate([kls[:1] - 1, kls[:-1]])
    run_start = (khs != prev_hi) | (kls != prev_lo)
    run_id = jnp.cumsum(run_start.astype(jnp.int32)) - 1

    has_store = jax.ops.segment_max(store_s.astype(jnp.int32), run_id, num_segments=n)
    has_cat = jax.ops.segment_max(cat_s.astype(jnp.int32), run_id, num_segments=n)
    run_valid = jax.ops.segment_max(
        (khs != _PAIR_SENTINEL).astype(jnp.int32), run_id, num_segments=n
    )
    has_store = has_store * run_valid
    has_cat = has_cat * run_valid
    both = jnp.sum((has_store & has_cat).astype(jnp.int32))
    store_only = jnp.sum((has_store & (1 - has_cat)).astype(jnp.int32))
    cat_only = jnp.sum((has_cat & (1 - has_store)).astype(jnp.int32))
    return store_only, cat_only, both


def _sharded_q97_columns(s_cust, s_item, c_cust, c_item, s_rv, c_rv,
                         capacity: int):
    """Per-device body over Column pytrees with nullable keys.

    ``s_rv``/``c_rv`` mark padding rows (row does not exist); a null *key*
    in an existing row is data, handled by the pair-key null semantics.
    The whole table rides one tagged exchange through the columnar
    shuffle (parallel/table_shuffle.py).
    """
    from spark_rapids_jni_tpu.columnar.column import Column
    from spark_rapids_jni_tpu.columnar.dtypes import INT64 as _I64
    from spark_rapids_jni_tpu.parallel.table_shuffle import shuffle_table

    dp = axis_size(DATA_AXIS)
    skh, skl = _pair_key(s_cust.data, s_cust.is_valid(),
                         s_item.data, s_item.is_valid(), side=1)
    ckh, ckl = _pair_key(c_cust.data, c_cust.is_valid(),
                         c_item.data, c_item.is_valid(), side=0)
    k_hi = jnp.concatenate([skh, ckh])
    k_lo = jnp.concatenate([skl, ckl])
    tag = jnp.concatenate(
        [jnp.ones(skh.shape, jnp.int8), jnp.zeros(ckh.shape, jnp.int8)]
    )
    row_valid = jnp.concatenate([s_rv, c_rv])

    mixed = k_hi ^ (k_lo * jnp.int64(-7046029254386353131))  # golden-ratio mix
    part = partition_of(mixed, dp)
    ex = shuffle_table(
        {
            "kh": Column(k_hi, None, _I64),
            "kl": Column(k_lo, None, _I64),
            "tag": Column(tag, None, _I64),
        },
        part, capacity, axis=DATA_AXIS, row_valid=row_valid,
    )
    so, co, b = _count_runs_pair(
        ex.columns["kh"].data, ex.columns["kl"].data,
        ex.columns["tag"].data == 1, ex.valid,
    )
    axes = (DATA_AXIS,)
    return Q97Out(
        jax.lax.psum(so, axes),
        jax.lax.psum(co, axes),
        jax.lax.psum(b, axes),
        jax.lax.psum(ex.dropped, axes),
    )


def make_distributed_q97_columns(mesh, capacity: int):
    """jit-compiled distributed q97 over nullable Column keys.

    Inputs: four int32 Columns (store customer/item, catalog customer/item,
    each optionally with a validity mask) plus two bool row-valid arrays for
    padding, all sharded over DATA_AXIS.
    """
    def body(s_cust, s_item, c_cust, c_item, s_rv, c_rv):
        return _sharded_q97_columns(s_cust, s_item, c_cust, c_item,
                                    s_rv, c_rv, capacity)

    step = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(P(DATA_AXIS) for _ in range(6)),
        out_specs=Q97Out(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(step)


# ---------------------------------------------------------------- governed --
# The host-driven control loop around the jitted step: batch admission through
# the memory arbiter, key-space split-and-retry, shuffle-capacity-grow retry.
# This is the protocol of RmmSpark.java:402-416 driving a real query.


@dataclasses.dataclass(frozen=True)
class Q97Batch:
    """One (sub-)batch of host rows: the store and catalog key columns.

    ``split_depth`` tracks which key-space bit splits this piece next;
    ``capacity`` is the per-destination shuffle bucket bound.
    """

    s_cust: np.ndarray
    s_item: np.ndarray
    c_cust: np.ndarray
    c_item: np.ndarray
    capacity: int
    split_depth: int = 0

    @property
    def rows(self) -> int:
        return len(self.s_cust) + len(self.c_cust)


def _split_hash(cust: np.ndarray, item: np.ndarray) -> np.ndarray:
    """Mixing hash of the composite key for key-space splitting (host)."""
    packed = (cust.astype(np.int64) << 32) | (item.astype(np.int64) & 0xFFFFFFFF)
    return packed.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)


def split_q97_batch(batch: Q97Batch):
    """Split the *key space* in half (bit ``split_depth`` of a mixing hash).

    Unlike a row split, a key-space split is exact for q97: every distinct
    key lands wholly in one child (both tables filtered by the same
    predicate), so the three presence counters sum across children.

    Each child also halves the shuffle capacity — the exchange buffers
    dominate the working set, and a child carries ~half the rows; if that
    undershoots, the grow retry recovers it.
    """
    bit = np.uint64(63 - batch.split_depth)
    parts = []
    for side in (0, 1):
        sm = ((_split_hash(batch.s_cust, batch.s_item) >> bit) & 1) == side
        cm = ((_split_hash(batch.c_cust, batch.c_item) >> bit) & 1) == side
        parts.append(dataclasses.replace(
            batch,
            s_cust=batch.s_cust[sm], s_item=batch.s_item[sm],
            c_cust=batch.c_cust[cm], c_item=batch.c_item[cm],
            capacity=max(16, batch.capacity // 2),
            split_depth=batch.split_depth + 1,
        ))
    return parts


def q97_working_set_bytes(batch: Q97Batch, dp: int) -> int:
    """Global working-set estimate: inputs + key/tag/valid stream + the
    [dp, capacity] send/recv exchange buffers + sort-merge workspace.
    Row terms use the QUANTIZED (padded) lengths run() actually uploads,
    so admission covers the real device footprint."""
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    n = (quantized_rows(len(batch.s_cust), dp)
         + quantized_rows(len(batch.c_cust), dp))
    per_row = 8 + 1 + 1  # key int64 + tag int8 + row_valid bool
    slots = dp * dp * batch.capacity
    return n * (8 + per_row) + 2 * slots * per_row + 2 * slots * 10


def _pad_to_multiple(arr: np.ndarray, mult: int, fill=0):
    """Pad to the dp-aligned POW2-QUANTIZED batch length (bounded compile
    variants — see parallel.shuffle.quantized_rows); pad rows are
    validity-masked out."""
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    pad = quantized_rows(len(arr), mult) - len(arr)
    if pad == 0:
        return arr, np.ones(len(arr), bool)
    padded = np.concatenate([arr, np.full(pad, fill, dtype=arr.dtype)])
    valid = np.concatenate([np.ones(len(arr), bool), np.zeros(pad, bool)])
    return padded, valid


def default_q97_capacity(total_rows: int, dp: int) -> int:
    """Safe-ish default per-(sender,dest) bucket bound: uniform share with
    a 2x skew margin (overflow is recoverable via the grow retry),
    pow2-rounded so data-dependent totals reuse one compiled step
    (capacity is a static shape parameter — the streamed-soak compiler
    OOM came from one executable per distinct capacity)."""
    from spark_rapids_jni_tpu.columnar.column import next_pow2

    raw = max(16, int(2 * total_rows / (dp * dp)) if dp > 1 else total_rows)
    return next_pow2(raw)


def run_q97_piece(mesh, piece: Q97Batch, *, sharding=None) -> Q97Out:
    """One FUSED launch of one q97 (sub-)batch through the compiled plan.

    The single-attempt core shared by :func:`run_distributed_q97` (which
    splits inline via run_with_split_retry) and the serving engine's q97
    handler (serve/executor.py, which splits by re-queueing halves) —
    both re-execute the whole fused program per piece, never a per-op
    disband.  Pad/upload/launch live in plans/runtime.execute_plan;
    compiled variants are cached on (plan structure, dtype signature,
    pow2 batch bucket).  Raises :class:`ShuffleCapacityExceeded` when
    rows overflowed the piece's static exchange capacity (the caller
    grows and re-runs).  ``sharding`` is accepted for API compatibility;
    the plan runtime derives placements from the plan itself.
    """
    from spark_rapids_jni_tpu.plans.runtime import execute_plan

    del sharding
    out = execute_plan(mesh, q97_plan(piece.capacity), {
        "store": {"cust": piece.s_cust, "item": piece.s_item},
        "catalog": {"cust": piece.c_cust, "item": piece.c_item},
    })
    return Q97Out(out["store_only"], out["catalog_only"], out["both"],
                  out["dropped"])


def combine_q97_outs(outs) -> Q97Out:
    """Sum partial presence counts (additive across key-space pieces)."""
    return Q97Out(
        sum(int(o.store_only) for o in outs),
        sum(int(o.catalog_only) for o in outs),
        sum(int(o.both) for o in outs),
        0,
    )


def run_distributed_q97(
    mesh,
    store,
    catalog,
    *,
    budget=None,
    task_id: int = 0,
    capacity: Optional[int] = None,
    max_split_depth: int = 8,
    manage_task: bool = True,
) -> Q97Out:
    """Governed distributed q97 over host (numpy) inputs.

    ``store``/``catalog`` are (customer_sk, item_sk) int32 array pairs.
    Every device launch is admitted through the memory arbiter: the working
    set is reserved before the step runs (mem/governed.py), RetryOOM retries,
    SplitAndRetryOOM splits the key space (exact), and shuffle-capacity
    overflow (dropped > 0) grows the exchange buffers and re-reserves.

    Reference protocol: RmmSpark.java:402-416; admission point analog of
    SparkResourceAdaptorJni.cpp:1731 do_allocate.

    ``manage_task=False`` joins a task context the caller already registered
    (the Spark shape: one dedicated thread registered per task runs many
    ops); the default registers/ends ``task_id`` itself.
    """
    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )

    dp = mesh.shape[DATA_AXIS]
    s_cust, s_item = (np.asarray(a, np.int32) for a in store)
    c_cust, c_item = (np.asarray(a, np.int32) for a in catalog)
    if budget is None:
        budget = default_device_budget()
    total = len(s_cust) + len(c_cust)
    cap0 = capacity if capacity is not None else default_q97_capacity(total, dp)
    batch = Q97Batch(s_cust, s_item, c_cust, c_item, capacity=cap0)

    def run(piece: Q97Batch) -> Q97Out:
        return run_q97_piece(mesh, piece)

    import contextlib

    ctx = (task_context(budget.gov, task_id) if manage_task
           else contextlib.nullcontext())
    with ctx:
        return run_with_split_retry(
            budget, batch,
            nbytes_of=lambda b: q97_working_set_bytes(b, dp),
            run=run,
            split=split_q97_batch,
            combine=combine_q97_outs,
            grow=lambda b: dataclasses.replace(b, capacity=2 * b.capacity),
            max_split_depth=max_split_depth,
        )
