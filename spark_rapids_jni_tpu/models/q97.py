"""Mini NDS q97: a distributed two-table join-count over the device mesh.

BASELINE.md staged config 5 is "NDS TPC-DS q5+q97 end-to-end"; this module is
the framework-native q97 core.  TPC-DS q97 counts (customer_sk, item_sk)
pairs sold in store only, catalog only, and both, from two fact tables —
i.e. a full outer join on a composite key reduced to presence counts:

    SELECT SUM(store_only), SUM(catalog_only), SUM(both) FROM
      (SELECT customer_sk, item_sk FROM store_sales GROUP BY 1,2) ss
      FULL OUTER JOIN
      (SELECT customer_sk, item_sk FROM catalog_sales GROUP BY 1,2) cs
      USING (customer_sk, item_sk)

Distributed plan (the Spark plan's TPU-native shape):

1. hash the composite key per row (Spark murmur3 row hashing, ops/hashing);
2. all_to_all shuffle BOTH tables by ``hash % ndev`` over the data axis —
   co-locating every distinct key on one owner shard (the exchange Spark
   does with its UCX shuffle, here one ICI collective);
3. per shard: sort the union of (key, source-tag) pairs and count
   equal-key runs by which sources appear — a static-shape sort-merge
   "join" (XLA-friendly: no dynamic hash table);
4. psum the three counters over the mesh.

Shuffled row counts are data-dependent; capacity is a static bound with
overflow reported (parallel/shuffle.py) — the caller retries with a larger
capacity exactly like a Spark shuffle spill retry.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu.ops.hashing import murmur3_raw_int64
from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_jni_tpu.parallel.shuffle import all_to_all_shuffle


class Q97Out(NamedTuple):
    store_only: jnp.ndarray  # scalar int32
    catalog_only: jnp.ndarray
    both: jnp.ndarray
    dropped: jnp.ndarray  # shuffle capacity overflows (0 == exact result)


def _composite_key(customer_sk: jnp.ndarray, item_sk: jnp.ndarray) -> jnp.ndarray:
    """One int64 key per (customer, item) pair.

    Both sks are positive 32-bit surrogate keys in TPC-DS, so packing is
    exact (no collisions), unlike hashing the pair.
    """
    return (customer_sk.astype(jnp.int64) << 32) | (
        item_sk.astype(jnp.int64) & 0xFFFFFFFF
    )


def _count_runs(keys: jnp.ndarray, is_store: jnp.ndarray, valid: jnp.ndarray):
    """Sort-merge presence counting over one shard's co-located rows.

    For every distinct valid key: did it appear with a store tag, a catalog
    tag, or both?  Returns (store_only, catalog_only, both) scalars.
    """
    # order by key; invalid rows sort last via the max sentinel
    sentinel = jnp.int64(0x7FFFFFFFFFFFFFFF)
    k = jnp.where(valid, keys, sentinel)
    order = jnp.argsort(k)
    ks = k[order]
    store_s = jnp.where(valid, is_store, False)[order]
    cat_s = jnp.where(valid, ~is_store, False)[order]

    # run starts: first element or key change
    n = ks.shape[0]
    prev = jnp.concatenate([ks[:1] - 1, ks[:-1]])
    run_start = ks != prev
    run_id = jnp.cumsum(run_start.astype(jnp.int32)) - 1

    # per-run presence via segment max (bounded by n runs)
    has_store = jax.ops.segment_max(
        store_s.astype(jnp.int32), run_id, num_segments=n
    )
    has_cat = jax.ops.segment_max(
        cat_s.astype(jnp.int32), run_id, num_segments=n
    )
    run_valid = jax.ops.segment_max(
        (ks != sentinel).astype(jnp.int32), run_id, num_segments=n
    )
    has_store = has_store * run_valid
    has_cat = has_cat * run_valid
    both = jnp.sum((has_store & has_cat).astype(jnp.int32))
    store_only = jnp.sum((has_store & (1 - has_cat)).astype(jnp.int32))
    cat_only = jnp.sum((has_cat & (1 - has_store)).astype(jnp.int32))
    return store_only, cat_only, both


def q97_local(store: tuple, catalog: tuple) -> Q97Out:
    """Single-chip q97 core over (customer_sk, item_sk) int arrays."""
    sk = _composite_key(*store)
    ck = _composite_key(*catalog)
    keys = jnp.concatenate([sk, ck])
    is_store = jnp.concatenate(
        [jnp.ones(sk.shape, bool), jnp.zeros(ck.shape, bool)]
    )
    so, co, b = _count_runs(keys, is_store, jnp.ones(keys.shape, bool))
    return Q97Out(so, co, b, jnp.int32(0))


def _sharded_q97(s_cust, s_item, c_cust, c_item, capacity: int):
    dp = jax.lax.axis_size(DATA_AXIS)
    sk = _composite_key(s_cust, s_item)
    ck = _composite_key(c_cust, c_item)

    # co-locate keys from BOTH tables with ONE tagged all_to_all: same bytes
    # moved, half the collective launches on the query hot path
    keys = jnp.concatenate([sk, ck])
    tag = jnp.concatenate(
        [jnp.ones(sk.shape, jnp.int8), jnp.zeros(ck.shape, jnp.int8)]
    )
    part = (murmur3_raw_int64(keys, 42) % jnp.uint32(dp)).astype(jnp.int32)
    ex = all_to_all_shuffle(
        {"k": keys, "tag": tag}, part, capacity, axis=DATA_AXIS
    )
    so, co, b = _count_runs(
        ex.columns["k"], ex.columns["tag"] == 1, ex.valid
    )
    axes = (DATA_AXIS,)
    return Q97Out(
        jax.lax.psum(so, axes),
        jax.lax.psum(co, axes),
        jax.lax.psum(b, axes),
        jax.lax.psum(ex.dropped, axes),
    )


def make_distributed_q97(mesh, capacity: int):
    """jit-compiled distributed q97 over ``mesh``'s data axis.

    Inputs: four [rows] int arrays sharded over DATA_AXIS (store customer/
    item, catalog customer/item).  ``capacity`` bounds per-destination
    shuffle buckets over the COMBINED row stream (both tables ride one
    tagged all_to_all); Q97Out.dropped > 0 means retry with a larger one.
    """
    step = jax.shard_map(
        functools.partial(_sharded_q97, capacity=capacity),
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=Q97Out(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(step)
