"""Flagship pipeline: a distributed columnar hash-aggregate query step.

This is the framework's "model": the representative NDS (TPC-DS-style) inner
loop that the BASELINE configs build toward — hash keys, bloom-filter
build+probe, shuffle rows to their owning partition, partial aggregation —
expressed as one jittable step over a 2D (data, model) mesh.

Parallelism mapping (the columnar-engine analog of NN-training axes):

- **dp**  = ``data`` mesh axis: rows of the batch are partition-parallel, the
  way Spark partitions map onto executors.
- **tp**  = ``model`` mesh axis: the bloom filter's bit array is sharded across
  chips; each chip owns a bit range and the probe combines per-shard verdicts
  with a psum (exactly a tensor-parallel reduce).
- **sp/ep analog** = the `all_to_all` shuffle: rows are exchanged to their hash
  owner, the same collective pattern sequence/expert parallelism uses.
- pp: no pipeline stages exist in a per-batch columnar engine; inter-op
  pipelining happens at the query-plan level (future work, see SURVEY.md §7.8).

Everything is static-shape and compiles once per batch geometry.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu.ops.hashing import murmur3_raw_int64, xxhash64_raw_int64
from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, axis_size, shard_map
from spark_rapids_jni_tpu.parallel.shuffle import all_to_all_shuffle, partition_of


class QueryStepConfig(NamedTuple):
    n_buckets: int = 1024  # aggregation hash-table size per shard
    bloom_bits: int = 1 << 16  # total bloom bit count (sharded over model axis)
    bloom_hashes: int = 3  # k probe hashes
    shuffle_capacity: int = 0  # 0 == safe default (local row count)


class QueryStepOut(NamedTuple):
    bucket_sums: jnp.ndarray  # [n_buckets] per data-shard partial aggregate
    bucket_counts: jnp.ndarray  # [n_buckets]
    bloom_bits: jnp.ndarray  # [bloom_bits/mp] this model-shard's bit range
    probe_hits: jnp.ndarray  # scalar: rows passing the bloom probe (global)
    total_rows: jnp.ndarray  # scalar: global row count (psum'd)
    dropped: jnp.ndarray  # scalar: shuffle capacity overflows (global)


def _bloom_positions(keys: jnp.ndarray, k: int, total_bits: int) -> jnp.ndarray:
    """[n, k] bit positions via double hashing from two murmur seeds.

    (Not the Spark sketch's exact bit layout — ops/bloom_filter.py owns
    Spark-serialization-compatible filters; this one is internal to the
    pipeline and only needs self-consistency.)
    """
    h1 = murmur3_raw_int64(keys, 0).astype(jnp.int64)
    h2 = murmur3_raw_int64(keys, 0x9747B28C).astype(jnp.int64)
    ks = jnp.arange(1, k + 1, dtype=jnp.int64)
    combined = h1[:, None] + ks[None, :] * h2[:, None]
    return combined % total_bits


def local_query_step(keys: jnp.ndarray, values: jnp.ndarray, cfg: QueryStepConfig):
    """Single-chip forward step: hash + bloom build/probe + bucket aggregation.

    This is the compile-checked `entry()` function of the framework.
    """
    h = xxhash64_raw_int64(keys)
    bucket = (h % jnp.uint64(cfg.n_buckets)).astype(jnp.int32)
    sums = jax.ops.segment_sum(values, bucket, num_segments=cfg.n_buckets)
    counts = jax.ops.segment_sum(
        # analyze: ignore[governed-allocation] - the compile-checked
        # entry() oracle: the count vector is n_buckets int32 beside the
        # resident fact columns; governed execution goes through
        # run_distributed / the plan tier, never this reference body
        jnp.ones_like(values, dtype=jnp.int32), bucket, num_segments=cfg.n_buckets
    )
    pos = _bloom_positions(keys, cfg.bloom_hashes, cfg.bloom_bits)
    bits = (
        # analyze: ignore[governed-allocation] - bloom_bits u8 bitmap,
        # same oracle path: sized by config, not by data, bounded small
        jnp.zeros((cfg.bloom_bits,), jnp.uint8).at[pos.reshape(-1)].max(1)
    )
    probed = bits[pos].astype(jnp.int32).sum(axis=1) == cfg.bloom_hashes
    return sums, counts, bits, probed.sum()


def _sharded_step(keys, values, cfg: QueryStepConfig):
    """The body run per device under shard_map over (data, model)."""
    dp = axis_size(DATA_AXIS)
    mp = axis_size(MODEL_AXIS)
    m_idx = jax.lax.axis_index(MODEL_AXIS)
    n_local = keys.shape[0]

    # 1. bloom build, bits sharded over the model axis (tp): each chip sets only
    #    bits in its owned range, then ORs partial bitmaps across the data axis.
    #    Positions mod the *effective* total (bits_per_shard * mp) so no bit
    #    range is orphaned when bloom_bits isn't divisible by the mesh.
    bits_per_shard = cfg.bloom_bits // mp
    pos = _bloom_positions(keys, cfg.bloom_hashes, bits_per_shard * mp)
    lo = m_idx.astype(jnp.int64) * bits_per_shard
    local_pos = pos.reshape(-1) - lo
    in_range = (local_pos >= 0) & (local_pos < bits_per_shard)
    local_bits = (
        jnp.zeros((bits_per_shard,), jnp.uint8)
        .at[jnp.where(in_range, local_pos, bits_per_shard)]
        .max(1, mode="drop")
    )
    local_bits = jax.lax.pmax(local_bits, DATA_AXIS)

    # 2. bloom probe (tp reduce): each model shard counts the probe bits it
    #    owns and has set; a row passes iff the psum over shards reaches k.
    probe_local_pos = pos - lo
    probe_in_range = (probe_local_pos >= 0) & (probe_local_pos < bits_per_shard)
    gathered = local_bits[jnp.clip(probe_local_pos, 0, bits_per_shard - 1)]
    set_here = jnp.where(probe_in_range, gathered.astype(jnp.int32), 0).sum(axis=1)
    set_total = jax.lax.psum(set_here, MODEL_AXIS)
    probe_hits = jax.lax.psum((set_total == cfg.bloom_hashes).sum(), DATA_AXIS)

    # 3. shuffle rows to their hash-owner partition (the sp/ep-style all_to_all)
    part = partition_of(keys, dp)
    capacity = cfg.shuffle_capacity or n_local
    shuffled = all_to_all_shuffle(
        {"keys": keys, "values": values}, part, capacity, axis=DATA_AXIS
    )

    # 4. local partial aggregation of owned rows into static buckets
    sk = shuffled.columns["keys"]
    sv = jnp.where(shuffled.valid, shuffled.columns["values"], 0)
    bucket = (xxhash64_raw_int64(sk) % jnp.uint64(cfg.n_buckets)).astype(jnp.int32)
    bucket = jnp.where(shuffled.valid, bucket, cfg.n_buckets)  # pad slot -> dropped
    sums = jax.ops.segment_sum(sv, bucket, num_segments=cfg.n_buckets + 1)[:-1]
    counts = jax.ops.segment_sum(
        shuffled.valid.astype(jnp.int32), bucket, num_segments=cfg.n_buckets + 1
    )[:-1]

    total_rows = jax.lax.psum(
        jnp.asarray(n_local, jnp.int32), (DATA_AXIS, MODEL_AXIS)
    ) // mp
    dropped = jax.lax.psum(shuffled.dropped, (DATA_AXIS, MODEL_AXIS)) // mp
    return QueryStepOut(sums, counts, local_bits, probe_hits, total_rows, dropped)


def make_distributed_query_step(mesh, cfg: QueryStepConfig):
    """jit-compiled full distributed step over ``mesh`` (axes data, model)."""
    step = shard_map(
        functools.partial(_sharded_step, cfg=cfg),
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=QueryStepOut(
            bucket_sums=P(DATA_AXIS),
            bucket_counts=P(DATA_AXIS),
            bloom_bits=P(MODEL_AXIS),
            probe_hits=P(),
            total_rows=P(),
            dropped=P(),
        ),
        check_vma=False,
    )
    return jax.jit(step)


def make_example_batch(n: int, key=None):
    """Tiny synthetic (keys int64, values int64) batch."""
    if key is None:
        key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    keys = jax.random.randint(k1, (n,), 0, 1 << 20, dtype=jnp.int64)
    values = jax.random.randint(k2, (n,), 0, 1000, dtype=jnp.int64)
    return keys, values
