"""Mini NDS q64: multi-join over sorted runs with framed running aggs.

TPC-DS q64 is the multi-way-join monster; the mini keeps its
order-sensitive core: store sales join TWO dims (item -> category/brand,
customer -> income band), filter, then analyze each (category, brand)
group in net-value order — row_number, a running net total, a
3-preceding ROWS-frame sum, and a running max:

    SELECT ..., ROW_NUMBER() OVER w rn,
           SUM(net)  OVER w run_net,
           SUM(net)  OVER (w ROWS 3 PRECEDING) net4,
           MAX(net)  OVER w peak
    FROM ... WHERE band >= b0
    WINDOW w AS (PARTITION BY category, brand ORDER BY net DESC, sid)
    QUALIFY rn <= k ORDER BY category, brand, rn

The range exchange keys on ``(category, brand)`` only — group
co-location is the window's correctness condition, and group-contiguous
partitions are the ordered concat's.  ``order_by`` includes the unique
``sid`` so every running aggregate is deterministic (no tie-order
ambiguity), unlike q67 which deliberately leaves price ties ambiguous to
exercise value-only rank semantics.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.ir import Bin, WinFunc, band_all, col, lit

__all__ = ["q64_plan", "q64_oracle", "make_q64_tables", "Q64_FIELDS"]

Q64_FIELDS = ("category", "brand", "sid", "net", "rn", "run_net",
              "net4", "peak")

#: the bounded ROWS frame (current row + 3 preceding)
_NET4_PRECEDING = 3


@functools.lru_cache(maxsize=32)
def q64_plan(k: int, n_items: int, n_custs: int, band0: int) -> ir.Plan:
    """The mini-q64 pipeline as ONE order-sensitive plan: two gather
    joins below a (category, brand) range exchange, framed window
    aggregates over sorted runs, top-``k`` rows per group, ordered row
    output."""
    scan = ir.Scan("store_sales", ("item_sk", "cust_sk", "qty", "price",
                                   "sid"))
    join_i = ir.GatherJoin(
        scan, ir.Dim("item", ("category", "brand")),
        key=col("item_sk"), base=lit(1),
        fields=(("category", "category"), ("brand", "brand")))
    join_c = ir.GatherJoin(
        join_i, ir.Dim("customer", ("band",)),
        key=col("cust_sk"), base=lit(1), fields=(("band", "band"),))
    net = ir.Project(join_c, (("net", Bin("mul", col("qty"),
                                          col("price"))),))
    valid = ir.Filter(net, band_all(
        Bin("ge", col("item_sk"), lit(1)),
        Bin("le", col("item_sk"), lit(int(n_items))),
        Bin("ge", col("cust_sk"), lit(1)),
        Bin("le", col("cust_sk"), lit(int(n_custs))),
        Bin("ge", col("band"), lit(int(band0)))))
    ex = ir.RangeExchange(
        valid, keys=((col("category"), True), (col("brand"), True)),
        fields=("category", "brand", "net", "sid"))
    win = ir.Window(
        ex, partition_by=(col("category"), col("brand")),
        order_by=((col("net"), False), (col("sid"), True)),
        funcs=(WinFunc("rn", "row_number", dtype="int32"),
               WinFunc("run_net", "sum", arg=col("net"), dtype="int64"),
               WinFunc("net4", "sum", arg=col("net"), dtype="int64",
                       preceding=_NET4_PRECEDING),
               WinFunc("peak", "max", arg=col("net"), dtype="int64")))
    top = ir.Filter(win, Bin("le", col("rn"), lit(int(k))))
    sink = ir.Sort(
        top, keys=((col("category"), True), (col("brand"), True),
                   (col("rn"), True)),
        fields=Q64_FIELDS)
    return ir.Plan("q64", (sink,))


def q64_oracle(tables: Dict[str, Dict[str, np.ndarray]], k: int,
               band0: int) -> Dict[str, np.ndarray]:
    """Pure-numpy unfused q64 (reference semantics, bit-exact)."""
    ss = tables["store_sales"]
    item = tables["item"]
    cust = tables["customer"]
    n_items = len(item["category"])
    n_custs = len(cust["band"])
    sel = ((ss["item_sk"] >= 1) & (ss["item_sk"] <= n_items)
           & (ss["cust_sk"] >= 1) & (ss["cust_sk"] <= n_custs))
    item_sk = ss["item_sk"][sel]
    cust_sk = ss["cust_sk"][sel]
    net = (ss["qty"][sel] * ss["price"][sel]).astype(np.int64)
    sid = ss["sid"][sel]
    category = item["category"][item_sk - 1]
    brand = item["brand"][item_sk - 1]
    band = cust["band"][cust_sk - 1]
    keep = band >= band0
    category, brand, net, sid = (category[keep], brand[keep], net[keep],
                                 sid[keep])

    order = np.lexsort((sid, -net, brand, category))
    cat_s, br_s, net_s, sid_s = (category[order], brand[order],
                                 net[order], sid[order])
    n = len(order)
    rn = np.zeros(n, np.int32)
    run_net = np.zeros(n, np.int64)
    net4 = np.zeros(n, np.int64)
    peak = np.zeros(n, np.int64)
    start = 0
    for i in range(1, n + 1):
        if i == n or cat_s[i] != cat_s[start] or br_s[i] != br_s[start]:
            g = net_s[start:i]
            rn[start:i] = np.arange(1, i - start + 1, dtype=np.int32)
            run_net[start:i] = np.cumsum(g)
            for j in range(len(g)):
                lo = max(0, j - _NET4_PRECEDING)
                net4[start + j] = int(g[lo:j + 1].sum())
            peak[start:i] = np.maximum.accumulate(g)
            start = i
    keep_k = rn <= k
    # already sorted by (category, brand, net desc, sid) == output order
    # for the kept rows (rn increases with that order)
    rows = {
        "category": cat_s[keep_k], "brand": br_s[keep_k],
        "sid": sid_s[keep_k], "net": net_s[keep_k],
        "rn": rn[keep_k], "run_net": run_net[keep_k],
        "net4": net4[keep_k], "peak": peak[keep_k],
    }
    rows["rows"] = np.int64(int(keep_k.sum()))
    return rows


def make_q64_tables(rows: int, n_items: int, n_custs: int,
                    n_cats: int = 6, n_brands: int = 4, n_bands: int = 5,
                    seed: int = 0) -> Dict[str, Dict[str, np.ndarray]]:
    """Synthetic q64 inputs: a sales fact plus item and customer dims."""
    rng = np.random.RandomState(seed)
    return {
        "store_sales": {
            "item_sk": rng.randint(1, n_items + 1, rows).astype(np.int64),
            "cust_sk": rng.randint(1, n_custs + 1, rows).astype(np.int64),
            "qty": rng.randint(1, 20, rows).astype(np.int64),
            "price": rng.randint(100, 5000, rows).astype(np.int64),
            "sid": np.arange(rows, dtype=np.int64),
        },
        "item": {
            "category": rng.randint(0, n_cats, n_items).astype(np.int64),
            "brand": rng.randint(0, n_brands, n_items).astype(np.int64),
        },
        "customer": {
            "band": rng.randint(0, n_bands, n_custs).astype(np.int64),
        },
    }
