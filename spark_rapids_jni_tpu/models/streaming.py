"""Out-of-core NDS execution: streamed generation + grace-hash bucketing.

BASELINE config 5 names TPC-DS SF100; no single host (and certainly not
this 1-core box) holds the fact stream in memory.  The scalable shape is
the classic external hash shuffle the reference relies on Spark for:

- facts are *generated/ingested in chunks* (bounded host memory),
- each chunk's rows are routed to a key-space bucket by a stable hash of
  the join key and appended to that bucket's spill file (columnar raw
  bytes, append-only — the host analog of parallel/table_shuffle.py's
  device exchange),
- each bucket then fits in memory by construction (total/n_buckets) and
  is executed as one governed distributed query piece; per-bucket results
  are additive because a (customer, item) pair lands in exactly one
  bucket on both sides.

On a pod the same plan maps bucket -> host group and spill file ->
ICI/DCN all_to_all (parallel/table_shuffle.py); here the seam between
"route rows" and "execute bucket" is identical, just disk-backed.
Parity: the reference delegates exactly this to Spark's external shuffle
(RapidsShuffleManager); q97 itself is
src/main/java: same join-count semantics as models/q97.py.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

__all__ = [
    "ExternalKeyShuffle",
    "generate_q97_chunks",
    "run_streaming_q97",
    "bucket_of_pairs",
]


def bucket_of_pairs(cust: np.ndarray, item: np.ndarray,
                    n_buckets: int) -> np.ndarray:
    """Stable key-space bucket of (customer, item) int32 pairs: splitmix64
    finalizer over the packed pair.  Any fixed mix works — both sides must
    agree, nothing else — but it must be *well mixed*: TPC-DS surrogate
    keys are dense integers, and `pair % n` would put all of one customer
    in one bucket."""
    with np.errstate(over="ignore"):
        k = ((cust.astype(np.int64).astype(np.uint64) << np.uint64(32))
             | (item.astype(np.int64).astype(np.uint64) & np.uint64(0xFFFFFFFF)))
        k = (k ^ (k >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        k = (k ^ (k >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        k = k ^ (k >> np.uint64(31))
        return (k % np.uint64(n_buckets)).astype(np.int64)


class ExternalKeyShuffle:
    """Disk-backed key-space partitioner for columnar int32 row chunks.

    ``append(side, bucket_ids, cols)`` routes a chunk's rows to per-
    (side, bucket) spill files (raw little-endian int32, append-only);
    ``read(side, bucket)`` materializes one bucket.  Peak host memory is
    one chunk during routing plus one bucket during execution.
    """

    def __init__(self, tmpdir: str, n_buckets: int,
                 columns: Tuple[str, ...] = ("cust", "item")):
        self.dir = tmpdir
        self.n_buckets = n_buckets
        self.columns = columns
        self.rows: Dict[Tuple[str, int], int] = {}
        # per-bucket hash modulus: initial buckets live at n_buckets;
        # split_bucket refines b -> (b, b+M) at modulus 2M (hash % M == b
        # implies hash % 2M in {b, b+M}, so refinement is consistent
        # across both sides — recursive grace hash)
        self._modulus: Dict[int, int] = {}
        os.makedirs(tmpdir, exist_ok=True)

    def _path(self, side: str, bucket: int, col: str) -> str:
        return os.path.join(self.dir, f"{side}.{bucket:04d}.{col}.bin")

    def append(self, side: str, bucket_ids: np.ndarray,
               cols: Tuple[np.ndarray, ...]) -> None:
        order = np.argsort(bucket_ids, kind="stable")
        sorted_ids = bucket_ids[order]
        # one contiguous slice per bucket present in the chunk
        uniq, starts = np.unique(sorted_ids, return_index=True)
        ends = np.append(starts[1:], len(sorted_ids))
        for b, s, e in zip(uniq.tolist(), starts.tolist(), ends.tolist()):
            for name, col in zip(self.columns, cols):
                with open(self._path(side, b, name), "ab") as f:
                    f.write(np.ascontiguousarray(
                        col[order[s:e]], dtype=np.int32).tobytes())
            key = (side, int(b))
            self.rows[key] = self.rows.get(key, 0) + int(e - s)

    def read(self, side: str, bucket: int) -> Tuple[np.ndarray, ...]:
        out = []
        for name in self.columns:
            path = self._path(side, bucket, name)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    out.append(np.frombuffer(f.read(), np.int32))
            else:
                out.append(np.zeros((0,), np.int32))
        return tuple(out)

    def split_bucket(self, bucket: int,
                     chunk_rows: int = 1 << 18) -> Tuple[int, int]:
        """Refine one bucket into two on DISK with bounded memory.

        Rows whose pair hash lands on ``bucket`` at modulus ``2M`` stay;
        the rest move to bucket ``bucket + M`` (files streamed in
        ``chunk_rows`` chunks — never the whole bucket in memory).  The
        recursive-grace-hash rung: a bucket that cannot fit the host
        budget splits into two that can, and per-bucket q97 counts stay
        additive because the refinement is key-space consistent.
        """
        m = self._modulus.get(bucket, self.n_buckets)
        new_bucket = bucket + m
        for side in ("store", "catalog"):
            if (side, bucket) not in self.rows:
                continue
            readers = [open(self._path(side, bucket, c), "rb")
                       for c in self.columns]
            keep_paths = [self._path(side, bucket, c) + ".keep"
                          for c in self.columns]
            keeps = [open(p, "wb") for p in keep_paths]
            moved = 0
            kept = 0
            try:
                while True:
                    chunk = [np.frombuffer(r.read(chunk_rows * 4), np.int32)
                             for r in readers]
                    if not len(chunk[0]):
                        break
                    stay = bucket_of_pairs(chunk[0], chunk[1],
                                           2 * m) == bucket
                    for col, arr, keep in zip(self.columns, chunk, keeps):
                        keep.write(np.ascontiguousarray(
                            arr[stay], np.int32).tobytes())
                        with open(self._path(side, new_bucket, col),
                                  "ab") as mv:
                            mv.write(np.ascontiguousarray(
                                arr[~stay], np.int32).tobytes())
                    kept += int(stay.sum())
                    moved += int((~stay).sum())
            finally:
                for f in readers + keeps:
                    f.close()
            for col, keep_path in zip(self.columns, keep_paths):
                os.replace(keep_path, self._path(side, bucket, col))
            self.rows[(side, bucket)] = kept
            if moved:
                self.rows[(side, new_bucket)] = (
                    self.rows.get((side, new_bucket), 0) + moved)
        self._modulus[bucket] = 2 * m
        self._modulus[new_bucket] = 2 * m
        return bucket, new_bucket

    def max_bucket_rows(self) -> int:
        """Largest combined (store+catalog) bucket — sizes the shuffle
        capacity once so every bucket reuses ONE compiled step."""
        per_bucket: Dict[int, int] = {}
        for (_side, b), n in self.rows.items():
            per_bucket[b] = per_bucket.get(b, 0) + n
        return max(per_bucket.values(), default=0)

    def close(self) -> None:
        for (side, b) in list(self.rows):
            for name in self.columns:
                try:
                    os.remove(self._path(side, b, name))
                except OSError:
                    pass
        self.rows.clear()


def generate_q97_chunks(sf: float, seed: int, chunk_rows: int
                        ) -> Iterator[Tuple[str, np.ndarray, np.ndarray]]:
    """Stream the q97 fact pair as ``(side, cust, item)`` chunks.

    Same marginal distribution as tpcds.generate_q97_tables (chunk c draws
    from a per-chunk seeded rng, so any prefix is reproducible without
    materializing the whole table — the streaming analog of dsdgen's
    parallel generation, which also seeds per partition)."""
    n = max(1000, int(2_800_000 * sf))
    n_cust = max(2, n // 14)
    for side_idx, side in enumerate(("store", "catalog")):
        done = 0
        chunk = 0
        while done < n:
            m = min(chunk_rows, n - done)
            rng = np.random.RandomState(
                (seed + 1_000_003 * side_idx + chunk) % (2**31 - 1))
            yield (side,
                   rng.randint(1, n_cust, m).astype(np.int32),
                   rng.randint(1, 18_000, m).astype(np.int32))
            done += m
            chunk += 1


def run_streaming_q97(
    mesh,
    chunks: Iterable[Tuple[str, np.ndarray, np.ndarray]],
    *,
    tmpdir: str,
    n_buckets: int = 16,
    budget=None,
    host_budget=None,
    task_id: int = 0,
    verify: bool = False,
    bucket_owner: Optional[Tuple[int, int]] = None,
) -> Tuple[Tuple[int, int, int], Optional[bool], Dict[str, int]]:
    """Out-of-core governed distributed q97 over streamed fact chunks.

    Returns ``((store_only, catalog_only, both), verified, stats)``.
    ``verified`` is per-bucket host-set oracle agreement (None when
    ``verify`` is off) — bucket-local sets are the whole point: the
    oracle's working set is also bounded by the bucket size.

    ``host_budget`` (a ``BudgetedResource(..., is_cpu=True)``) governs the
    HOST-side bucket materialization: each bucket's row bytes are reserved
    through the arbiter's CPU path before the bucket is read back, so a
    multi-tenant host blocks/wakes on pinned-host pressure exactly like
    device pressure (the reference governs CPU allocations through the
    same state machine — SparkResourceAdaptorJni.cpp is_for_cpu paths).

    ``bucket_owner=(proc_id, nprocs)`` restricts execution to the buckets
    this participant OWNS (``b % nprocs == proc_id``) — the pod-scale
    deployment shape: host groups partition the bucket space, per-owner
    counts stay additive, and the global answer is the sum of the owners'
    results (tests/streaming_worker.py drives this across two real OS
    processes).
    """
    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )
    from spark_rapids_jni_tpu.models.q97 import (
        default_q97_capacity,
        run_distributed_q97,
    )
    from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

    if bucket_owner is not None:
        proc_id, nprocs = bucket_owner
        if not (0 <= proc_id < nprocs):
            raise ValueError(f"bucket_owner {bucket_owner}: need "
                             "0 <= proc_id < nprocs")
    if budget is None:
        budget = default_device_budget()
    shuffle = ExternalKeyShuffle(tmpdir, n_buckets)
    rows_in = 0
    try:
        for side, cust, item in chunks:
            ids = bucket_of_pairs(cust, item, n_buckets)
            rows_in += len(cust)
            if bucket_owner is not None:
                # spool ONLY owned buckets: (nprocs-1)/nprocs of the
                # shuffle disk IO is someone else's and never read here
                mine = (ids % bucket_owner[1]) == bucket_owner[0]
                if not mine.any():
                    continue
                ids, cust, item = ids[mine], cust[mine], item[mine]
            shuffle.append(side, ids, (cust, item))

        dp = mesh.shape[DATA_AXIS]
        # ONE capacity for every bucket piece -> one compiled step reused
        cap = default_q97_capacity(shuffle.max_bucket_rows(), dp)
        totals = [0, 0, 0]
        verified: Optional[bool] = True if verify else None
        def run_bucket(b: int):
            store_b = shuffle.read("store", b)
            cat_b = shuffle.read("catalog", b)
            out = run_distributed_q97(
                mesh, store_b, cat_b, budget=budget, task_id=task_id,
                capacity=cap, manage_task=False)
            got = (int(out.store_only), int(out.catalog_only), int(out.both))
            oracle_ok = True
            if verify:
                s = set(zip(store_b[0].tolist(), store_b[1].tolist()))
                c = set(zip(cat_b[0].tolist(), cat_b[1].tolist()))
                oracle_ok = got == (len(s - c), len(c - s), len(s & c))
            return got, oracle_ok

        def piece_rows(b: int) -> int:
            return (shuffle.rows.get(("store", b), 0)
                    + shuffle.rows.get(("catalog", b), 0))

        n_splits = [0]

        def split_piece(b: int):
            # recursive grace hash: re-partition the oversized bucket on
            # disk into two key-space-consistent halves (counts stay
            # additive); run_with_split_retry then reserves each half
            n_splits[0] += 1
            return shuffle.split_bucket(b)

        def combine_pieces(rs):
            return (tuple(sum(r[0][i] for r in rs) for i in range(3)),
                    all(r[1] for r in rs))

        with task_context(budget.gov, task_id):
            for b in range(n_buckets):
                if bucket_owner is not None and \
                        b % bucket_owner[1] != bucket_owner[0]:
                    continue
                if piece_rows(b) == 0:
                    continue
                if host_budget is not None:
                    # the canonical retry driver brackets the host
                    # reservation: RetryOOM from multi-tenant pressure
                    # re-runs the bucket; an over-budget bucket splits on
                    # disk instead of crashing the stream
                    got, oracle_ok = run_with_split_retry(
                        host_budget, b,
                        nbytes_of=lambda bb: piece_rows(bb) * 8,  # 2x i32
                        run=run_bucket,
                        split=split_piece,
                        combine=combine_pieces,
                    )
                else:
                    got, oracle_ok = run_bucket(b)
                if verify and not oracle_ok:
                    verified = False
                for i in range(3):
                    totals[i] += got[i]
        stats = {
            "rows_in": rows_in,
            "n_buckets": n_buckets,
            "max_bucket_rows": shuffle.max_bucket_rows(),
            "capacity": cap,
        }
        if host_budget is not None:
            # snapshot, NOT reset_peak(): the budget may be shared by
            # concurrent tenants, and mutating a caller-owned high-water
            # mark would race; this is the global peak so far by contract
            stats["host_peak_reserved"] = host_budget.peak
            stats["bucket_splits"] = n_splits[0]
        return tuple(totals), verified, stats
    finally:
        shuffle.close()
