"""Out-of-core NDS execution: streamed generation + grace-hash bucketing.

BASELINE config 5 names TPC-DS SF100; no single host (and certainly not
this 1-core box) holds the fact stream in memory.  The scalable shape is
the classic external hash shuffle the reference relies on Spark for:

- facts are *generated/ingested in chunks* (bounded host memory),
- each chunk's rows are routed to a key-space bucket by a stable hash of
  the join key and appended to that bucket's spill file — JCUDF row
  batches carrying the FULL table (validity, strings, decimal128) through
  io/spill.py's ExternalTableShuffle, the host analog of
  parallel/table_shuffle.py's device exchange,
- each bucket then fits in memory by construction (total/n_buckets) and
  is executed as one governed distributed query piece; per-bucket results
  are additive because a (customer, item) pair lands in exactly one
  bucket on both sides.

On a pod the same plan maps bucket -> host group and spill file ->
ICI/DCN all_to_all (parallel/table_shuffle.py); here the seam between
"route rows" and "execute bucket" is identical, just disk-backed.
Parity: the reference delegates exactly this to Spark's external shuffle
(RapidsShuffleManager) carrying its JCUDF row batches
(row_conversion.cu:574); q97 itself is src/main/java: same join-count
semantics as models/q97.py.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from spark_rapids_jni_tpu.io.spill import ExternalTableShuffle, pair_mix64

__all__ = [
    "ExternalTableShuffle",
    "generate_q97_chunks",
    "run_streaming_q97",
    "bucket_of_pairs",
    "q97_spill_shuffle",
    "generate_q5_chunks",
    "run_streaming_q5",
]


def bucket_of_pairs(cust: np.ndarray, item: np.ndarray,
                    n_buckets: int) -> np.ndarray:
    """Stable key-space bucket of (customer, item) int32 pairs: splitmix64
    finalizer over the packed pair (io/spill.py pair_mix64).  Any fixed mix
    works — both sides must agree, nothing else — but it must be *well
    mixed*: TPC-DS surrogate keys are dense integers, and ``pair % n``
    would put all of one customer in one bucket."""
    return (pair_mix64(cust, item) % np.uint64(n_buckets)).astype(np.int64)


def _pair_key_hash(cols) -> np.ndarray:
    """ExternalTableShuffle key hash for the q97 (cust, item) int32 pair —
    identical mix to :func:`bucket_of_pairs`, so bucket placement agrees
    with the ownership filter and the legacy tests' expectations."""
    return pair_mix64(np.asarray(cols[0].data), np.asarray(cols[1].data))


def q97_spill_shuffle(tmpdir: str, n_buckets: int) -> ExternalTableShuffle:
    """The q97 fact-pair spill shuffle: two non-null int32 key columns in
    JCUDF rows, routed by the pair hash."""
    from spark_rapids_jni_tpu.columnar.dtypes import INT32

    return ExternalTableShuffle(
        tmpdir, n_buckets, [INT32, INT32], key_indices=(0, 1),
        key_hash=_pair_key_hash)


def generate_q97_chunks(sf: float, seed: int, chunk_rows: int
                        ) -> Iterator[Tuple[str, np.ndarray, np.ndarray]]:
    """Stream the q97 fact pair as ``(side, cust, item)`` chunks.

    Same marginal distribution as tpcds.generate_q97_tables (chunk c draws
    from a per-chunk seeded rng, so any prefix is reproducible without
    materializing the whole table — the streaming analog of dsdgen's
    parallel generation, which also seeds per partition)."""
    n = max(1000, int(2_800_000 * sf))
    n_cust = max(2, n // 14)
    for side_idx, side in enumerate(("store", "catalog")):
        done = 0
        chunk = 0
        while done < n:
            m = min(chunk_rows, n - done)
            rng = np.random.RandomState(
                (seed + 1_000_003 * side_idx + chunk) % (2**31 - 1))
            yield (side,
                   rng.randint(1, n_cust, m).astype(np.int32),
                   rng.randint(1, 18_000, m).astype(np.int32))
            done += m
            chunk += 1


# ------------------------------------------------------------ streamed q5 --
# q5's aggregates are per-(channel, dim_sk) segment sums — additive over any
# disjoint row partition — so the grace hash needs no join co-location; it
# routes by the GROUP key (dim sk) per channel anyway, which makes every
# (channel, sk) group bucket-local and the per-bucket oracle exact without a
# global materialize.  Facts spill as full JCUDF tables (nullable keys +
# int64 money) through one ExternalTableShuffle with six sides:
# "{channel}.{sales|ret}".


def generate_q5_chunks(sf: float, seed: int, chunk_rows: int,
                       null_pct: float = 0.04):
    """Stream the q5 fact tables as ``(channel, kind, arrays)`` chunks.

    Same totals as tpcds.generate_q5_data (n_sales = 40k*sf scaled down by
    channel, returns = sales/8) with per-chunk seeded rngs, so any prefix
    is reproducible without materializing a table.  ``kind`` is "sales"
    (m1=price, m2=profit) or "ret" (m1=amt, m2=loss).
    """
    from spark_rapids_jni_tpu.models.tpcds import CHANNELS, q5_dims

    dims = q5_dims()
    d0 = int(dims.date_sk[0])
    n_dates = len(dims.date_sk)
    for ci, name in enumerate(CHANNELS):
        n_dim = dims.channel_size(name)
        n_sales = max(8, int(40_000 * sf) // (ci + 1))
        for ki, (kind, total, m2_lo, m2_hi) in enumerate(
                (("sales", n_sales, -100_00, 200_00),
                 ("ret", max(4, n_sales // 8), 0, 80_00))):
            done = 0
            chunk = 0
            while done < total:
                m = min(chunk_rows, total - done)
                rng = np.random.RandomState(
                    (seed + 7_000_003 * ci + 500_009 * ki + chunk)
                    % (2**31 - 1))
                sk = rng.randint(1, n_dim + 1, m).astype(np.int32)
                sk_valid = rng.rand(m) >= null_pct
                date = rng.randint(d0, d0 + n_dates, m).astype(np.int32)
                date_valid = rng.rand(m) >= null_pct
                yield (name, kind, {
                    "sk": np.where(sk_valid, sk, 0).astype(np.int32),
                    "sk_valid": sk_valid,
                    "date": np.where(date_valid, date, 0).astype(np.int32),
                    "date_valid": date_valid,
                    "m1": rng.randint(0, 500_00, m).astype(np.int64),
                    "m2": rng.randint(m2_lo, m2_hi, m).astype(np.int64),
                })
                done += m
                chunk += 1


def _q5_side_facts(shuffle: ExternalTableShuffle, channel: str, bucket: int):
    """Decode one channel's (sales, ret) spill sides of one bucket into the
    q5 fact-array dict the partials step consumes."""
    out = {}
    for kind, names in (("sales", ("sales_sk", "sales_date",
                                   "sales_price", "sales_profit")),
                        ("ret", ("ret_sk", "ret_date",
                                 "ret_amt", "ret_loss"))):
        cols = shuffle.read(f"{channel}.{kind}", bucket)
        n = len(np.asarray(cols[0].data))
        for col, cname in zip(cols, names):
            out[cname] = np.asarray(col.data)
        for key_col, cname in ((cols[0], f"{kind}_sk"),
                               (cols[1], f"{kind}_date")):
            out[f"{cname}_valid"] = (
                np.ones(n, bool) if key_col.validity is None
                else np.asarray(key_col.validity))
    return out


def run_streaming_q5(
    mesh,
    chunks,
    *,
    tmpdir: str,
    n_buckets: int = 16,
    budget=None,
    host_budget=None,
    task_id: int = 0,
    verify: bool = False,
    bucket_owner: Optional[Tuple[int, int]] = None,
):
    """Out-of-core governed distributed q5 over streamed fact chunks.

    Returns ``(rows, verified, stats)`` where ``rows`` is the full
    ROLLUP(channel, id) result.  Each bucket runs through ONE cached
    compiled partials step (geometry is the dim side, bucket-independent);
    per-bucket partial vectors sum into the global answer because every
    aggregate is additive over the disjoint bucket rows.  ``verify``
    checks each bucket against the numpy oracle
    (models.q5.q5_host_channel_partials) — bucket-local, bounded memory.

    Host staging is governed like streamed q97: the bucket's ACTUAL
    spill-file bytes are reserved on the arbiter's CPU path; an
    over-budget bucket recursively splits on disk (partials stay additive
    under ANY row partition, so key-space splits are trivially exact).
    """
    from spark_rapids_jni_tpu.columnar.column import Column
    from spark_rapids_jni_tpu.columnar.dtypes import INT32, INT64
    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )
    from spark_rapids_jni_tpu.models.q5 import (
        ChannelPartials,
        add_partials,
        q5_host_channel_partials,
        q5_rollup,
        run_q5_partials,
    )
    from spark_rapids_jni_tpu.models.tpcds import CHANNELS, q5_dims

    if bucket_owner is not None:
        proc_id, nprocs = bucket_owner
        if not (0 <= proc_id < nprocs):
            raise ValueError(f"bucket_owner {bucket_owner}: need "
                             "0 <= proc_id < nprocs")
    if budget is None:
        budget = default_device_budget()
    dims = q5_dims()
    schema = [INT32, INT32, INT64, INT64]  # sk, date, m1, m2
    shuffle = ExternalTableShuffle(tmpdir, n_buckets, schema,
                                   key_indices=(0,))
    rows_in = 0
    try:
        for channel, kind, ch in chunks:
            rows_in += len(ch["sk"])
            cols = [
                Column(ch["sk"], ch["sk_valid"], INT32),
                Column(ch["date"], ch["date_valid"], INT32),
                Column(ch["m1"], None, INT64),
                Column(ch["m2"], None, INT64),
            ]
            hashes = shuffle.row_hashes(cols)
            if bucket_owner is not None:
                ids = (hashes % np.uint64(n_buckets)).astype(np.int64)
                mine = (ids % bucket_owner[1]) == bucket_owner[0]
                if not mine.any():
                    continue
                cols = [Column(np.asarray(col.data)[mine],
                               None if col.validity is None
                               else np.asarray(col.validity)[mine],
                               col.dtype) for col in cols]
                hashes = hashes[mine]
            shuffle.append(f"{channel}.{kind}", cols, hashes=hashes)

        verified: Optional[bool] = True if verify else None

        def run_bucket(b: int):
            batch = {name: _q5_side_facts(shuffle, name, b)
                     for name in CHANNELS}
            per = run_q5_partials(
                mesh, batch,
                date_sk=dims.date_sk, date_days=dims.date_days,
                n_dims=dims.n_dims,
                lo=dims.sales_date_lo, hi=dims.sales_date_hi,
                budget=budget, task_id=task_id, manage_task=False)
            oracle_ok = True
            if verify:
                for name, n_dim in zip(CHANNELS, dims.n_dims):
                    want = q5_host_channel_partials(
                        batch[name], n_dim, dims.date_sk, dims.date_days,
                        dims.sales_date_lo, dims.sales_date_hi)
                    got = per[name]
                    oracle_ok = oracle_ok and all(
                        np.array_equal(np.asarray(g, np.int64),
                                       np.asarray(w, np.int64))
                        for g, w in zip(got, want))
            return per, oracle_ok

        n_splits = [0]

        def split_piece(b: int):
            n_splits[0] += 1
            return shuffle.split_bucket(b)

        def combine_pieces(rs):
            acc = rs[0][0]
            for per, _ok in rs[1:]:
                acc = add_partials(acc, per)
            return acc, all(ok for _p, ok in rs)

        totals = None
        with task_context(budget.gov, task_id):
            for b in range(n_buckets):
                if bucket_owner is not None and \
                        b % bucket_owner[1] != bucket_owner[0]:
                    continue
                if shuffle.bucket_rows(b) == 0:
                    continue
                if host_budget is not None:
                    per, oracle_ok = run_with_split_retry(
                        host_budget, b,
                        nbytes_of=shuffle.bucket_nbytes,
                        run=run_bucket,
                        split=split_piece,
                        combine=combine_pieces,
                    )
                else:
                    per, oracle_ok = run_bucket(b)
                if verify and not oracle_ok:
                    verified = False
                totals = per if totals is None else add_partials(totals, per)
        if totals is None:  # no owned rows at all
            totals = {name: ChannelPartials(
                np.zeros(nd, np.int64), np.zeros(nd, np.int64),
                np.zeros(nd, np.int64), np.zeros(nd, np.int32))
                for name, nd in zip(CHANNELS, dims.n_dims)}
        rows = q5_rollup(totals, dims.dim_id)
        stats = {
            "rows_in": rows_in,
            "n_buckets": n_buckets,
            "max_bucket_rows": shuffle.max_bucket_rows(),
        }
        if host_budget is not None:
            stats["host_peak_reserved"] = host_budget.peak
            stats["bucket_splits"] = n_splits[0]
        return rows, verified, stats
    finally:
        shuffle.close()


def run_streaming_q97(
    mesh,
    chunks: Iterable[Tuple[str, np.ndarray, np.ndarray]],
    *,
    tmpdir: str,
    n_buckets: int = 16,
    budget=None,
    host_budget=None,
    task_id: int = 0,
    verify: bool = False,
    bucket_owner: Optional[Tuple[int, int]] = None,
) -> Tuple[Tuple[int, int, int], Optional[bool], Dict[str, int]]:
    """Out-of-core governed distributed q97 over streamed fact chunks.

    Returns ``((store_only, catalog_only, both), verified, stats)``.
    ``verified`` is per-bucket host-set oracle agreement (None when
    ``verify`` is off) — bucket-local sets are the whole point: the
    oracle's working set is also bounded by the bucket size.

    ``host_budget`` (a ``BudgetedResource(..., is_cpu=True)``) governs the
    HOST-side bucket materialization: each bucket's ACTUAL spill-file bytes
    are reserved through the arbiter's CPU path before the bucket is read
    back, so a multi-tenant host blocks/wakes on pinned-host pressure
    exactly like device pressure (the reference governs CPU allocations
    through the same state machine — SparkResourceAdaptorJni.cpp is_for_cpu
    paths).

    ``bucket_owner=(proc_id, nprocs)`` restricts execution to the buckets
    this participant OWNS (``b % nprocs == proc_id``) — the pod-scale
    deployment shape: host groups partition the bucket space, per-owner
    counts stay additive, and the global answer is the sum of the owners'
    results (tests/streaming_worker.py drives this across real OS
    processes).
    """
    from spark_rapids_jni_tpu.columnar.column import Column
    from spark_rapids_jni_tpu.columnar.dtypes import INT32
    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )
    from spark_rapids_jni_tpu.models.q97 import (
        default_q97_capacity,
        run_distributed_q97,
    )
    from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

    if bucket_owner is not None:
        proc_id, nprocs = bucket_owner
        if not (0 <= proc_id < nprocs):
            raise ValueError(f"bucket_owner {bucket_owner}: need "
                             "0 <= proc_id < nprocs")
    if budget is None:
        budget = default_device_budget()
    shuffle = q97_spill_shuffle(tmpdir, n_buckets)
    rows_in = 0
    try:
        for side, cust, item in chunks:
            rows_in += len(cust)
            hashes = pair_mix64(cust, item)
            if bucket_owner is not None:
                # spool ONLY owned buckets: (nprocs-1)/nprocs of the
                # shuffle disk IO is someone else's and never read here
                ids = (hashes % np.uint64(n_buckets)).astype(np.int64)
                mine = (ids % bucket_owner[1]) == bucket_owner[0]
                if not mine.any():
                    continue
                cust, item, hashes = cust[mine], item[mine], hashes[mine]
            shuffle.append(
                side,
                [Column(cust, None, INT32), Column(item, None, INT32)],
                hashes=hashes)

        dp = mesh.shape[DATA_AXIS]
        # ONE capacity for every bucket piece -> one compiled step reused
        cap = default_q97_capacity(shuffle.max_bucket_rows(), dp)
        totals = [0, 0, 0]
        verified: Optional[bool] = True if verify else None

        def read_pair(side: str, b: int):
            cols = shuffle.read(side, b)
            return (np.asarray(cols[0].data, np.int32),
                    np.asarray(cols[1].data, np.int32))

        def run_bucket(b: int):
            store_b = read_pair("store", b)
            cat_b = read_pair("catalog", b)
            out = run_distributed_q97(
                mesh, store_b, cat_b, budget=budget, task_id=task_id,
                capacity=cap, manage_task=False)
            got = (int(out.store_only), int(out.catalog_only), int(out.both))
            oracle_ok = True
            if verify:
                s = set(zip(store_b[0].tolist(), store_b[1].tolist()))
                c = set(zip(cat_b[0].tolist(), cat_b[1].tolist()))
                oracle_ok = got == (len(s - c), len(c - s), len(s & c))
            return got, oracle_ok

        n_splits = [0]

        def split_piece(b: int):
            # recursive grace hash: re-partition the oversized bucket on
            # disk into two key-space-consistent halves (counts stay
            # additive); run_with_split_retry then reserves each half
            n_splits[0] += 1
            return shuffle.split_bucket(b)

        def combine_pieces(rs):
            return (tuple(sum(r[0][i] for r in rs) for i in range(3)),
                    all(r[1] for r in rs))

        with task_context(budget.gov, task_id):
            for b in range(n_buckets):
                if bucket_owner is not None and \
                        b % bucket_owner[1] != bucket_owner[0]:
                    continue
                if shuffle.bucket_rows(b) == 0:
                    continue
                if host_budget is not None:
                    # the canonical retry driver brackets the host
                    # reservation — sized by the bucket's ACTUAL spill-file
                    # bytes: RetryOOM from multi-tenant pressure re-runs
                    # the bucket; an over-budget bucket splits on disk
                    # instead of crashing the stream
                    got, oracle_ok = run_with_split_retry(
                        host_budget, b,
                        nbytes_of=shuffle.bucket_nbytes,
                        run=run_bucket,
                        split=split_piece,
                        combine=combine_pieces,
                    )
                else:
                    got, oracle_ok = run_bucket(b)
                if verify and not oracle_ok:
                    verified = False
                for i in range(3):
                    totals[i] += got[i]
        stats = {
            "rows_in": rows_in,
            "n_buckets": n_buckets,
            "max_bucket_rows": shuffle.max_bucket_rows(),
            "capacity": cap,
        }
        if host_budget is not None:
            # snapshot, NOT reset_peak(): the budget may be shared by
            # concurrent tenants, and mutating a caller-owned high-water
            # mark would race; this is the global peak so far by contract
            stats["host_peak_reserved"] = host_budget.peak
            stats["bucket_splits"] = n_splits[0]
        return tuple(totals), verified, stats
    finally:
        shuffle.close()
