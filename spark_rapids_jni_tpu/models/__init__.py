from spark_rapids_jni_tpu.models.nds import (
    QueryStepConfig,
    QueryStepOut,
    local_query_step,
    make_distributed_query_step,
    make_example_batch,
)
from spark_rapids_jni_tpu.models.q97 import (
    Q97Batch,
    Q97Out,
    make_distributed_q97,
    q97_local,
    run_distributed_q97,
    split_q97_batch,
)

__all__ = [
    "QueryStepConfig",
    "QueryStepOut",
    "Q97Batch",
    "Q97Out",
    "local_query_step",
    "make_distributed_query_step",
    "make_distributed_q97",
    "make_example_batch",
    "q97_local",
    "run_distributed_q97",
    "split_q97_batch",
]
