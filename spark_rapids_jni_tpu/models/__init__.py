from spark_rapids_jni_tpu.models.nds import (
    QueryStepConfig,
    QueryStepOut,
    local_query_step,
    make_distributed_query_step,
    make_example_batch,
)
from spark_rapids_jni_tpu.models.q3 import (
    Q3Row,
    make_distributed_q3,
    q3_local,
    run_distributed_q3,
    run_distributed_q3_columns,
)
from spark_rapids_jni_tpu.models.q5 import (
    Q5Row,
    make_distributed_q5,
    q5_local,
    run_distributed_q5,
)
from spark_rapids_jni_tpu.models.q97 import (
    Q97Batch,
    Q97Out,
    combine_q97_outs,
    make_distributed_q97,
    make_distributed_q97_columns,
    q97_local,
    run_distributed_q97,
    run_q97_piece,
    split_q97_batch,
)
from spark_rapids_jni_tpu.models.tpcds import (
    Q3Data,
    Q5Data,
    generate_q3_data,
    generate_q5_data,
)

__all__ = [
    "QueryStepConfig",
    "QueryStepOut",
    "Q3Data",
    "Q3Row",
    "Q5Data",
    "Q5Row",
    "Q97Batch",
    "Q97Out",
    "generate_q3_data",
    "generate_q5_data",
    "make_distributed_q3",
    "q3_local",
    "run_distributed_q3",
    "run_distributed_q3_columns",
    "make_distributed_q5",
    "make_distributed_q97_columns",
    "q5_local",
    "run_distributed_q5",
    "local_query_step",
    "make_distributed_query_step",
    "make_distributed_q97",
    "make_example_batch",
    "combine_q97_outs",
    "q97_local",
    "run_distributed_q97",
    "run_q97_piece",
    "split_q97_batch",
]
