from spark_rapids_jni_tpu.models.nds import (
    QueryStepConfig,
    QueryStepOut,
    local_query_step,
    make_distributed_query_step,
    make_example_batch,
)

__all__ = [
    "QueryStepConfig",
    "QueryStepOut",
    "local_query_step",
    "make_distributed_query_step",
    "make_example_batch",
]
