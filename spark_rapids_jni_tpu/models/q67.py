"""Mini NDS q67: top-k ranked rows per category — the windowed-rank tier.

TPC-DS q67 ranks store sales within each category by sales and keeps the
top 100 rows per category:

    SELECT * FROM (
      SELECT ..., RANK() OVER (PARTITION BY i_category
                               ORDER BY sumsales DESC) rk ...)
    WHERE rk <= 100 ORDER BY i_category, rk, ...

The TPU-native shape (the first order-sensitive compiled plan):

1. **dim join** (map side): category gathered from the replicated item
   dim by surrogate key;
2. **range exchange** on ``category`` — every category co-located on one
   reduce partition AND partitions contiguous in category order, so the
   per-partition outputs concatenate into global order (splitters
   sampled at dispatch, plans/window.py);
3. **window** (reduce side): ``rank``/``dense_rank`` over
   ``price DESC`` within each category run — ties share a rank, and
   rank depends only on key VALUES, so the filtered row set is
   deterministic no matter how a stable sort broke the ties;
4. **filter** ``rk <= k`` and a **Sort sink** on
   ``(category, rk, sid)`` — ``sid`` is a unique row id, making the
   emitted row ORDER bit-reproducible too.

:func:`q67_oracle` is the pure-numpy unfused twin the parity tests pin
the compiled plan against (the q5_local_unfused discipline), and
:func:`topk_sales_plan` is the global top-k variant whose
``RangeExchange.limit`` pushes the partial top-k below the wire.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.ir import Bin, WinFunc, band_all, col, lit

__all__ = ["q67_plan", "q67_oracle", "make_q67_tables",
           "topk_sales_plan", "naive_sort_limit_plan", "topk_oracle"]

#: output row columns, in plan field order
Q67_FIELDS = ("category", "item_sk", "price", "sid", "rk", "drk")


@functools.lru_cache(maxsize=32)
def q67_plan(k: int, n_items: int) -> ir.Plan:
    """The whole mini-q67 pipeline as ONE order-sensitive plan.

    ``k`` (rank cutoff) and ``n_items`` (dim size, validity bound) are
    plan structure, like q97's capacity.  Contains a RangeExchange —
    runs split across the serve shuffle plane or through
    ``run_range_plan_local``.
    """
    scan = ir.Scan("store_sales", ("item_sk", "price", "sid"))
    join = ir.GatherJoin(
        scan, ir.Dim("item", ("category",)),
        key=col("item_sk"), base=lit(1),
        fields=(("category", "category"),))
    valid = ir.Filter(join, band_all(
        Bin("ge", col("item_sk"), lit(1)),
        Bin("le", col("item_sk"), lit(int(n_items)))))
    ex = ir.RangeExchange(
        valid, keys=((col("category"), True),),
        fields=("category", "item_sk", "price", "sid"))
    win = ir.Window(
        ex, partition_by=(col("category"),),
        order_by=((col("price"), False),),
        funcs=(WinFunc("rk", "rank", dtype="int32"),
               WinFunc("drk", "dense_rank", dtype="int32")))
    top = ir.Filter(win, Bin("le", col("rk"), lit(int(k))))
    sink = ir.Sort(
        top, keys=((col("category"), True), (col("rk"), True),
                   (col("sid"), True)),
        fields=Q67_FIELDS)
    return ir.Plan("q67", (sink,))


def q67_oracle(tables: Dict[str, Dict[str, np.ndarray]],
               k: int) -> Dict[str, np.ndarray]:
    """Pure-numpy unfused q67: the reference semantics the compiled plan
    must match bit for bit (same output dict shape as the plan path:
    field vectors + ``rows``)."""
    ss = tables["store_sales"]
    item = tables["item"]
    n_items = len(item["category"])
    sel = (ss["item_sk"] >= 1) & (ss["item_sk"] <= n_items)
    item_sk = ss["item_sk"][sel]
    price = ss["price"][sel]
    sid = ss["sid"][sel]
    category = item["category"][item_sk - 1]

    # rank within category by price desc: count rows strictly greater
    order = np.lexsort((sid, -price, category))
    cat_s, price_s, item_s, sid_s = (category[order], price[order],
                                     item_sk[order], sid[order])
    n = len(order)
    rk = np.zeros(n, np.int32)
    drk = np.zeros(n, np.int32)
    start = 0
    for i in range(1, n + 1):
        if i == n or cat_s[i] != cat_s[start]:
            p = price_s[start:i]
            uniq = np.unique(-p)  # ascending over negated = desc prices
            for j in range(start, i):
                rk[j] = 1 + int(np.sum(p > price_s[j]))
                drk[j] = 1 + int(np.searchsorted(uniq, -price_s[j]))
            start = i
    keep = rk <= k
    out_order = np.lexsort((sid_s[keep], rk[keep], cat_s[keep]))
    rows = {
        "category": cat_s[keep][out_order],
        "item_sk": item_s[keep][out_order],
        "price": price_s[keep][out_order],
        "sid": sid_s[keep][out_order],
        "rk": rk[keep][out_order].astype(np.int32),
        "drk": drk[keep][out_order].astype(np.int32),
    }
    rows["rows"] = np.int64(int(keep.sum()))
    return rows


def make_q67_tables(rows: int, n_items: int, n_cats: int,
                    seed: int = 0) -> Dict[str, Dict[str, np.ndarray]]:
    """Synthetic q67 inputs: a store_sales fact (with a unique ``sid``
    row id for deterministic ordering) and an item dim mapping surrogate
    keys to categories."""
    rng = np.random.RandomState(seed)
    return {
        "store_sales": {
            "item_sk": rng.randint(1, n_items + 1, rows).astype(np.int64),
            "price": rng.randint(100, 10000, rows).astype(np.int64),
            "sid": np.arange(rows, dtype=np.int64),
        },
        "item": {
            "category": rng.randint(0, n_cats, n_items).astype(np.int64),
        },
    }


# ------------------------------------------------------------- global topk


@functools.lru_cache(maxsize=32)
def topk_sales_plan(k: int) -> ir.Plan:
    """Global top-k sales by price: ``RangeExchange.limit`` pushes the
    partial top-k below the shuffle (each map shard sends at most ``k``
    rows), the TopK sink takes the per-partition first k, and the
    ordered combine truncates the concat back to k."""
    keys = ((col("price"), False), (col("sid"), True))
    scan = ir.Scan("store_sales", ("price", "sid"))
    ex = ir.RangeExchange(scan, keys=keys, fields=("price", "sid"),
                          limit=int(k))
    sink = ir.TopK(ex, keys=keys, k=int(k), fields=("price", "sid"))
    return ir.Plan("topk_sales", (sink,))


@functools.lru_cache(maxsize=32)
def naive_sort_limit_plan(k: int) -> ir.Plan:
    """The strawman: full global sort, THEN limit — identical answer,
    every row crosses the wire.  Exists so the top-k byte-reduction is a
    measured assertion (tests + bench), not a claim."""
    keys = ((col("price"), False), (col("sid"), True))
    scan = ir.Scan("store_sales", ("price", "sid"))
    ex = ir.RangeExchange(scan, keys=keys, fields=("price", "sid"))
    sink = ir.TopK(ex, keys=keys, k=int(k), fields=("price", "sid"))
    return ir.Plan("topk_sales_naive", (sink,))


def topk_oracle(tables, k: int) -> Dict[str, np.ndarray]:
    """Numpy top-k by (price desc, sid asc)."""
    ss = tables["store_sales"]
    order = np.lexsort((ss["sid"], -ss["price"]))[:k]
    return {"price": ss["price"][order], "sid": ss["sid"][order],
            "rows": np.int64(len(order))}
