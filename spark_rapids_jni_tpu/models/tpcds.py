"""TPC-DS-shaped synthetic data for the NDS model pipelines (q5, q97).

A small, seeded generator producing the tables q5 touches, with the shapes
that make TPC-DS data hard: nullable foreign keys, string dimension ids,
and decimal(7,2) money columns (stored as unscaled int64 cents, the Arrow/
Spark DECIMAL representation).  Scale factor ``sf`` linearly sizes the fact
tables; sf=0.01 ~ 1.4k fact rows total, sf=1 ~ 140k.

This stands in for the reference benchmarks' generate_input.cu data layer
(/root/reference/src/main/cpp/benchmarks/common/generate_input.cu) on the
NDS side: not a full dsdgen port, but faithful to the column shapes the
query plans exercise.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

__all__ = ["Q3Data", "Q5Data", "Q5Dims", "q5_dims", "generate_q3_data",
           "generate_q5_data", "generate_q97_tables", "write_q97_parquet",
           "CHANNELS"]

# (channel label, fact prefix, dim id prefix) for q5's three channel unions
CHANNELS = ("store", "catalog", "web")

_D0 = 2450815  # d_date_sk epoch base the generator uses (arbitrary julian-ish)


@dataclasses.dataclass
class ChannelTables:
    """One channel's fact pair + dimension, column-oriented numpy arrays.

    Sales fact: (sk -> dim key, date_sk, ext_sales_price, net_profit);
    returns fact: (sk, date_sk, return_amt, net_loss).  Money columns are
    unscaled cents (decimal scale 2).  Nullable columns carry a mask
    (True == valid), mirroring Column validity.
    """

    sales_sk: np.ndarray
    sales_sk_valid: np.ndarray
    sales_date: np.ndarray
    sales_date_valid: np.ndarray
    sales_price: np.ndarray  # int64 cents
    sales_profit: np.ndarray  # int64 cents

    ret_sk: np.ndarray
    ret_sk_valid: np.ndarray
    ret_date: np.ndarray
    ret_date_valid: np.ndarray
    ret_amt: np.ndarray
    ret_loss: np.ndarray

    dim_sk: np.ndarray  # [n_dim] surrogate keys (dense, 1..n)
    dim_id: list  # [n_dim] business id strings (e.g. AAAAAAAAAABAAAAA-ish)


@dataclasses.dataclass
class Q5Data:
    channels: Dict[str, ChannelTables]
    date_sk: np.ndarray  # date_dim surrogate keys
    date_days: np.ndarray  # d_date as days-since-epoch ints
    sales_date_lo: int  # the q5 14-day window, as day numbers
    sales_date_hi: int


def _dim_ids(prefix: str, n: int, rng) -> list:
    # TPC-DS business ids are fixed-width uppercase strings
    out = []
    for i in range(n):
        digits = []
        v = i
        for _ in range(8):
            digits.append(chr(ord("A") + v % 26))
            v //= 26
        out.append(prefix + "".join(reversed(digits)))
    return out


def _money(rng, n: int, lo=0, hi=500_00) -> np.ndarray:
    return rng.randint(lo, hi, n).astype(np.int64)


def _nullable(rng, vals: np.ndarray, null_pct: float):
    valid = rng.rand(len(vals)) >= null_pct
    return np.where(valid, vals, 0).astype(vals.dtype), valid


@dataclasses.dataclass
class Q5Dims:
    """The q5 dimension side: date_dim + per-channel business dims.

    Deterministic and sf-independent (dims are tiny; facts scale), so a
    streamed producer and a bucket executor can each rebuild them without
    exchanging anything — the replicated-broadcast-dim shape of the plan.
    """

    date_sk: np.ndarray
    date_days: np.ndarray
    sales_date_lo: int
    sales_date_hi: int
    dim_sk: Dict[str, np.ndarray]
    dim_id: Dict[str, list]

    @property
    def n_dims(self):
        return tuple(len(self.dim_sk[n]) for n in CHANNELS)

    def channel_size(self, name: str) -> int:
        return len(self.dim_sk[name])


def q5_dims() -> Q5Dims:
    """Build the (deterministic) q5 dimension tables."""
    n_dates = 120
    lo = 30
    dim_sk = {}
    dim_id = {}
    for ci, name in enumerate(CHANNELS):
        n_dim = max(3, int(6 * (ci + 1)))
        dim_sk[name] = np.arange(1, n_dim + 1, dtype=np.int32)
        dim_id[name] = _dim_ids(name[0].upper(), n_dim, None)
    return Q5Dims(
        date_sk=np.arange(_D0, _D0 + n_dates, dtype=np.int32),
        date_days=np.arange(n_dates, dtype=np.int32),
        sales_date_lo=lo,
        sales_date_hi=lo + 14,  # q5's 14-day window
        dim_sk=dim_sk,
        dim_id=dim_id,
    )


def generate_q5_data(sf: float = 0.01, seed: int = 0,
                     null_pct: float = 0.04) -> Q5Data:
    """Generate the q5 table set at scale factor ``sf``."""
    rng = np.random.RandomState(seed)
    dims = q5_dims()
    date_sk = dims.date_sk
    date_days = dims.date_days
    n_dates = len(date_sk)
    lo = dims.sales_date_lo
    hi = dims.sales_date_hi

    channels: Dict[str, ChannelTables] = {}
    for ci, name in enumerate(CHANNELS):
        n_dim = dims.channel_size(name)
        n_sales = max(8, int(40_000 * sf) // (ci + 1))
        n_ret = max(4, n_sales // 8)
        dim_sk = dims.dim_sk[name]

        s_sk, s_skv = _nullable(
            rng, rng.randint(1, n_dim + 1, n_sales).astype(np.int32), null_pct)
        s_dt, s_dtv = _nullable(
            rng, rng.randint(_D0, _D0 + n_dates, n_sales).astype(np.int32),
            null_pct)
        r_sk, r_skv = _nullable(
            rng, rng.randint(1, n_dim + 1, n_ret).astype(np.int32), null_pct)
        r_dt, r_dtv = _nullable(
            rng, rng.randint(_D0, _D0 + n_dates, n_ret).astype(np.int32),
            null_pct)

        channels[name] = ChannelTables(
            sales_sk=s_sk, sales_sk_valid=s_skv,
            sales_date=s_dt, sales_date_valid=s_dtv,
            sales_price=_money(rng, n_sales),
            sales_profit=_money(rng, n_sales, -100_00, 200_00),
            ret_sk=r_sk, ret_sk_valid=r_skv,
            ret_date=r_dt, ret_date_valid=r_dtv,
            ret_amt=_money(rng, n_ret),
            ret_loss=_money(rng, n_ret, 0, 80_00),
            dim_sk=dim_sk,
            dim_id=dims.dim_id[name],
        )
    return Q5Data(channels, date_sk, date_days, lo, hi)


@dataclasses.dataclass
class Q3Data:
    """q3 table set: store_sales fact + item and date_dim dimensions.

    item: dense surrogate keys 1..n_items, a brand string per item (many
    items share a brand), and a manufacturer id (the query's filter).
    date_dim: dense keys with (d_year, d_moy) attributes.
    """

    ss_item_sk: np.ndarray
    ss_item_sk_valid: np.ndarray
    ss_sold_date_sk: np.ndarray
    ss_sold_date_sk_valid: np.ndarray
    ss_ext_sales_price: np.ndarray  # int64 cents (decimal scale 2)

    item_sk: np.ndarray  # [n_items] dense 1..n
    item_brand_id: np.ndarray  # [n_items] int32
    item_manufact_id: np.ndarray  # [n_items] int32
    brand_names: list  # [n_brands] strings; brand_id b -> brand_names[b-1]

    date_sk: np.ndarray  # [n_dates] dense keys (from _D0)
    date_year: np.ndarray
    date_moy: np.ndarray

    manufact_id: int  # the query's i_manufact_id literal
    moy: int  # the query's d_moy literal


def generate_q3_data(sf: float = 0.01, seed: int = 0,
                     null_pct: float = 0.04) -> Q3Data:
    """Generate the q3 table set at scale factor ``sf``."""
    rng = np.random.RandomState(seed + 3)
    n_items = max(12, int(200 * sf))
    n_brands = max(5, n_items // 4)
    n_manufact = 8
    n_dates = 3 * 365
    n_sales = max(16, int(120_000 * sf))

    item_sk = np.arange(1, n_items + 1, dtype=np.int32)
    item_brand_id = rng.randint(1, n_brands + 1, n_items).astype(np.int32)
    item_manufact_id = rng.randint(1, n_manufact + 1, n_items).astype(np.int32)
    brand_names = [f"corpbrand #{b}" for b in range(1, n_brands + 1)]

    date_sk = np.arange(_D0, _D0 + n_dates, dtype=np.int32)
    date_year = (1998 + np.arange(n_dates) // 365).astype(np.int32)
    date_moy = (1 + (np.arange(n_dates) % 365) // 31).astype(np.int32)

    i_sk, i_v = _nullable(
        rng, rng.randint(1, n_items + 1, n_sales).astype(np.int32), null_pct)
    d_sk, d_v = _nullable(
        rng, rng.randint(_D0, _D0 + n_dates, n_sales).astype(np.int32),
        null_pct)

    return Q3Data(
        ss_item_sk=i_sk, ss_item_sk_valid=i_v,
        ss_sold_date_sk=d_sk, ss_sold_date_sk_valid=d_v,
        ss_ext_sales_price=_money(rng, n_sales),
        item_sk=item_sk, item_brand_id=item_brand_id,
        item_manufact_id=item_manufact_id, brand_names=brand_names,
        date_sk=date_sk, date_year=date_year, date_moy=date_moy,
        manufact_id=int(rng.randint(1, n_manufact + 1)), moy=11,
    )


def generate_q97_tables(sf: float, seed: int):
    """The q97 fact pair: (customer_sk, item_sk) int32 arrays per channel,
    ~SF-proportional (SF1 store_sales is ~2.9M rows)."""
    rng = np.random.RandomState(seed)
    n = max(1000, int(2_800_000 * sf))
    store = (rng.randint(1, max(2, n // 14), n).astype(np.int32),
             rng.randint(1, 18_000, n).astype(np.int32))
    catalog = (rng.randint(1, max(2, n // 14), n).astype(np.int32),
               rng.randint(1, 18_000, n).astype(np.int32))
    return store, catalog


def write_q97_parquet(outdir: str, sf: float = 0.05, seed: int = 42,
                      rows_per_group: int = 65536):
    """Write the q97 fact pair as multi-row-group parquet files.

    Each file carries the two join keys plus money columns the query does
    NOT touch — so split planning via the footer (row-group midpoint
    filter) and column pruning are both load-bearing when the NDS harness
    reads these back (``nds_harness --input``).  Returns the two paths.
    """
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(outdir, exist_ok=True)
    store, catalog = generate_q97_tables(sf, seed)
    rng = np.random.RandomState(seed + 97)
    paths = {}
    for name, prefix, (cust, item) in (
            ("store_sales", "ss", store), ("catalog_sales", "cs", catalog)):
        n = len(cust)
        table = pa.table({
            f"{prefix}_customer_sk": pa.array(cust, pa.int32()),
            f"{prefix}_item_sk": pa.array(item, pa.int32()),
            # pruned by the q97 read schema: never materialized
            f"{prefix}_ext_sales_price": pa.array(
                _money(rng, n), pa.int64()),
            f"{prefix}_net_profit": pa.array(
                rng.rand(n) * 100.0, pa.float64()),
        })
        path = os.path.join(outdir, f"{name}.parquet")
        pq.write_table(table, path, row_group_size=rows_per_group)
        paths[name] = path
    return paths["store_sales"], paths["catalog_sales"]
