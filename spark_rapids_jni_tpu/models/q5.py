"""NDS q5: the three-channel sales/returns rollup (BASELINE config 5).

TPC-DS q5 unions store, catalog and web channel activity over a 14-day
window, computing per-business-id sales, returns and profit, grouped by
ROLLUP(channel, id).  The TPU-native plan per channel:

1. **date dim join** (device): membership of each fact row's date_sk in the
   filtered date_dim window via searchsorted over the (tiny, replicated)
   dim — the broadcast-join analog of the Spark plan.
2. **null-key semantics**: fact rows with null dim/date foreign keys drop
   out of the inner joins, exactly as in SQL.
3. **partial aggregation** (device): masked ``segment_sum`` into dense
   per-dim-sk buckets — sales cents, return cents, profit cents, and a
   contributing-row count.  Money is decimal(7,2) as unscaled int64 cents;
   sums widen to decimal(17,2) which stays int64-exact (Spark's own sum
   widening keeps precision+10).
4. **exchange**: ``psum`` of the partial vectors over the data axis (the
   aggregation all-reduce — rows never need a shuffle because the dim
   space is dense and small, the degenerate broadcast-join case).
5. **rollup** (host, tiny): (channel, id) rows -> channel totals -> grand
   total, with the string business ids attached from the dim table.

The governed runner admits every launch through the memory arbiter and
splits fact rows on SplitAndRetryOOM — row splits are exact here because
every aggregate is additive.
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_jni_tpu.models.tpcds import CHANNELS, Q5Data
from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, shard_map

__all__ = [
    "Q5Row",
    "q5_local",
    "make_distributed_q5",
    "run_distributed_q5",
    "run_q5_partials",
    "q5_rollup",
    "q5_host_channel_partials",
    "ChannelPartials",
    "add_partials",
]


class Q5Row(NamedTuple):
    """One result row: ROLLUP levels use None for grouped-out columns."""

    channel: object  # str | None
    id: object  # str | None
    sales: int  # cents
    returns_: int
    profit: int


class ChannelPartials(NamedTuple):
    """Per-dim-sk partial aggregates of one channel — ADDITIVE over any
    disjoint row partition (the invariant row splits and the streamed
    bucket pipeline rely on)."""

    sales: jnp.ndarray  # int64[n_dim]
    returns_: jnp.ndarray
    profit: jnp.ndarray
    count: jnp.ndarray  # int32[n_dim] contributing rows (sales+returns)


_ChannelPartials = ChannelPartials


def add_partials(
    a: Dict[str, ChannelPartials], b: Dict[str, ChannelPartials]
) -> Dict[str, ChannelPartials]:
    """Element-wise sum of per-channel partial dicts (the additivity every
    split/bucket combine relies on)."""
    return {name: ChannelPartials(*(x + y for x, y in zip(a[name], b[name])))
            for name in a}


def _window_member(date, date_valid, dim_sk, dim_days, lo, hi):
    """Inner-join membership of fact date_sk in the filtered date dim."""
    idx = jnp.clip(jnp.searchsorted(dim_sk, date), 0, dim_sk.shape[0] - 1)
    hit = dim_sk[idx] == date
    in_win = (dim_days[idx] >= lo) & (dim_days[idx] < hi)
    return date_valid & hit & in_win


def _masked_segment(values, sk, ok, n_dim, dtype=jnp.int64):
    """segment_sum of values into 1-based sk buckets, masked rows dropped."""
    bucket = jnp.where(ok, sk.astype(jnp.int32) - 1, n_dim)
    return jax.ops.segment_sum(
        jnp.where(ok, values, 0).astype(dtype), bucket, num_segments=n_dim + 1
    )[:-1]


def _channel_partials(ch, n_dim, dim_sk, dim_days, lo, hi) -> _ChannelPartials:
    """One shard's partial aggregates for one channel.

    ``ch`` is a dict of this channel's fact arrays (see models/tpcds.py
    ChannelTables field names).
    """
    s_ok = ch["sales_sk_valid"] & (ch["sales_sk"] >= 1) & (
        ch["sales_sk"] <= n_dim
    ) & _window_member(ch["sales_date"], ch["sales_date_valid"],
                       dim_sk, dim_days, lo, hi)
    r_ok = ch["ret_sk_valid"] & (ch["ret_sk"] >= 1) & (
        ch["ret_sk"] <= n_dim
    ) & _window_member(ch["ret_date"], ch["ret_date_valid"],
                       dim_sk, dim_days, lo, hi)

    sales = _masked_segment(ch["sales_price"], ch["sales_sk"], s_ok, n_dim)
    profit_s = _masked_segment(ch["sales_profit"], ch["sales_sk"], s_ok, n_dim)
    returns_ = _masked_segment(ch["ret_amt"], ch["ret_sk"], r_ok, n_dim)
    loss = _masked_segment(ch["ret_loss"], ch["ret_sk"], r_ok, n_dim)
    count = (
        _masked_segment(jnp.ones_like(ch["sales_sk"]), ch["sales_sk"],
                        s_ok, n_dim, jnp.int32)
        + _masked_segment(jnp.ones_like(ch["ret_sk"]), ch["ret_sk"],
                          r_ok, n_dim, jnp.int32)
    )
    return _ChannelPartials(sales, returns_, profit_s - loss, count)


def _facts_of(ch_tables) -> Dict[str, np.ndarray]:
    return {
        "sales_sk": ch_tables.sales_sk,
        "sales_sk_valid": ch_tables.sales_sk_valid,
        "sales_date": ch_tables.sales_date,
        "sales_date_valid": ch_tables.sales_date_valid,
        "sales_price": ch_tables.sales_price,
        "sales_profit": ch_tables.sales_profit,
        "ret_sk": ch_tables.ret_sk,
        "ret_sk_valid": ch_tables.ret_sk_valid,
        "ret_date": ch_tables.ret_date,
        "ret_date_valid": ch_tables.ret_date_valid,
        "ret_amt": ch_tables.ret_amt,
        "ret_loss": ch_tables.ret_loss,
    }


def q5_local(data: Q5Data) -> List[Q5Row]:
    """Single-chip q5: per-channel partials + host rollup."""
    dim_sk = jnp.asarray(data.date_sk)
    dim_days = jnp.asarray(data.date_days)
    per_channel = {}
    for name in CHANNELS:
        ch = data.channels[name]
        parts = _channel_partials(
            {k: jnp.asarray(v) for k, v in _facts_of(ch).items()},
            len(ch.dim_sk), dim_sk, dim_days,
            data.sales_date_lo, data.sales_date_hi,
        )
        per_channel[name] = jax.tree.map(np.asarray, parts)
    return q5_rollup(per_channel,
                     {n: data.channels[n].dim_id for n in CHANNELS})


def q5_rollup(per_channel: Dict[str, _ChannelPartials],
              dim_ids: Dict[str, List[str]]) -> List[Q5Row]:
    """ROLLUP(channel, id) formatting: leaf rows, channel totals, grand
    total — ordered like the SQL output (channel, id, nulls last).
    ``dim_ids`` maps channel -> business-id strings (dim_sk order)."""
    rows: List[Q5Row] = []
    g_sales = g_ret = g_prof = 0
    for name in CHANNELS:
        p = per_channel[name]
        ids = dim_ids[name]
        c_sales = c_ret = c_prof = 0
        leaf: List[Q5Row] = []
        for i in range(len(ids)):
            if int(p.count[i]) == 0:
                continue  # group absent from the filtered join
            s, r, pr = int(p.sales[i]), int(p.returns_[i]), int(p.profit[i])
            leaf.append(Q5Row(name, ids[i], s, r, pr))
            c_sales += s
            c_ret += r
            c_prof += pr
        rows.extend(sorted(leaf, key=lambda q: q.id))
        rows.append(Q5Row(name, None, c_sales, c_ret, c_prof))
        g_sales += c_sales
        g_ret += c_ret
        g_prof += c_prof
    rows.append(Q5Row(None, None, g_sales, g_ret, g_prof))
    return rows


# ------------------------------------------------------------- distributed --


def _sharded_q5(channel_facts, dim_sk, dim_days, n_dims: Tuple[int, ...],
                lo: int, hi: int):
    """Per-device body: partials for all three channels, psum'd."""
    out = []
    for name, n_dim in zip(CHANNELS, n_dims):
        p = _channel_partials(channel_facts[name], n_dim, dim_sk, dim_days,
                              lo, hi)
        out.append(_ChannelPartials(*(
            jax.lax.psum(x, (DATA_AXIS,)) for x in p
        )))
    return tuple(out)


def make_distributed_q5(mesh, data: Q5Data):
    """jit-compiled distributed q5 partials over ``mesh``'s data axis.

    Facts are sharded over DATA_AXIS; the date dim is replicated.  Returns
    a function of the sharded channel-fact pytree producing replicated
    per-channel partial vectors (feed to :func:`q5_rollup`).

    The step depends on ``data`` only through small scalars, so it is
    LRU-cached like q97's: an executor looping over many batches of one
    geometry must reuse ONE traced program, not leak a fresh jit wrapper
    (and its compiled-executable cache entry) per call — the soak tool
    caught exactly that as ~3 MB RSS per iteration (tools/soak.py).
    """
    n_dims = tuple(len(data.channels[n].dim_sk) for n in CHANNELS)
    return _q5_step_cached(mesh, n_dims, data.sales_date_lo,
                           data.sales_date_hi)


@functools.lru_cache(maxsize=32)
def _q5_step_cached(mesh, n_dims: tuple, lo: int, hi: int):
    from spark_rapids_jni_tpu.obs.seam import COMPILE, seam

    with seam(COMPILE, "q5_step"):
        body = functools.partial(_sharded_q5, n_dims=n_dims, lo=lo, hi=hi)
        step = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(), P()),
            out_specs=tuple(_ChannelPartials(P(), P(), P(), P())
                            for _ in CHANNELS),
            check_vma=False,
        )
        return jax.jit(step)


def _pad_channel(facts: Dict[str, np.ndarray], dp: int) -> Dict[str, np.ndarray]:
    """Pad fact arrays to the dp-aligned pow2-quantized length (bounded
    compile variants, parallel.shuffle.quantized_rows); pad rows get
    invalid keys, so they drop out of the joins like any null-keyed row."""
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    out = {}
    n_s = len(facts["sales_sk"])
    n_r = len(facts["ret_sk"])
    pad_s = quantized_rows(n_s, dp) - n_s
    pad_r = quantized_rows(n_r, dp) - n_r
    for k, v in facts.items():
        pad = pad_s if k.startswith("sales") else pad_r
        if pad == 0:
            out[k] = v
            continue
        fill = np.zeros(pad, dtype=v.dtype)
        out[k] = np.concatenate([v, fill])
    if pad_s:
        out["sales_sk_valid"][-pad_s:] = False
    if pad_r:
        out["ret_sk_valid"][-pad_r:] = False
    return out


def _split_channel(facts: Dict[str, np.ndarray]):
    """Halve fact rows (exact: all q5 aggregates are additive over rows)."""
    halves = []
    n_s = len(facts["sales_sk"])
    n_r = len(facts["ret_sk"])
    for side in (0, 1):
        sel = {}
        s_sl = slice(0, n_s // 2) if side == 0 else slice(n_s // 2, n_s)
        r_sl = slice(0, n_r // 2) if side == 0 else slice(n_r // 2, n_r)
        for k, v in facts.items():
            sel[k] = v[s_sl] if k.startswith("sales") else v[r_sl]
        halves.append(sel)
    return halves


def run_q5_partials(
    mesh,
    batch: Dict[str, Dict[str, np.ndarray]],
    *,
    date_sk: np.ndarray,
    date_days: np.ndarray,
    n_dims: Tuple[int, ...],
    lo: int,
    hi: int,
    budget=None,
    task_id: int = 0,
    manage_task: bool = True,
) -> Dict[str, _ChannelPartials]:
    """Governed distributed q5 PARTIALS over a host fact batch.

    ``batch`` maps channel -> fact-array dict (the _facts_of field names);
    the step is LRU-cached on (mesh, n_dims, lo, hi), so every caller with
    one dim geometry — in-memory q5, every bucket of streamed q5 — reuses
    ONE compiled program.  Every launch is admitted through the memory
    arbiter; SplitAndRetryOOM halves fact rows (exact — all aggregates are
    additive) and partials combine by addition.
    """
    import contextlib

    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )

    if budget is None:
        budget = default_device_budget()
    dp = int(np.prod([mesh.shape[a] for a in (DATA_AXIS,)]))
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    step = _q5_step_cached(mesh, tuple(n_dims), lo, hi)
    dim_sk = jax.device_put(date_sk, rep)
    dim_days = jax.device_put(date_days, rep)

    def nbytes_of(b):
        # quantized (padded) lengths: what run() actually uploads
        from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

        total = sum(quantized_rows(len(v), dp) * v.itemsize
                    for ch in b.values() for v in ch.values())
        return total * 3  # inputs + masks/buckets + partials

    def run(b):
        from spark_rapids_jni_tpu.obs.seam import COLLECTIVE, TRANSFER, seam

        with seam(TRANSFER, "q5_batch_upload"):
            dev = {
                n: {k: jax.device_put(np.ascontiguousarray(v), sharding)
                    for k, v in _pad_channel(ch, dp).items()}
                for n, ch in b.items()
            }
        with seam(COLLECTIVE, "launch:q5_step"):
            out = step(dev, dim_sk, dim_days)
            jax.block_until_ready(out)
        return {n: jax.tree.map(np.asarray, p)
                for n, p in zip(CHANNELS, out)}

    def split(b):
        parts = {n: _split_channel(ch) for n, ch in b.items()}
        return [{n: parts[n][0] for n in b}, {n: parts[n][1] for n in b}]

    def combine(results):
        acc = results[0]
        for r in results[1:]:
            acc = add_partials(acc, r)
        return acc

    ctx = (task_context(budget.gov, task_id) if manage_task
           else contextlib.nullcontext())
    with ctx:
        return run_with_split_retry(
            budget, batch,
            nbytes_of=nbytes_of, run=run, split=split, combine=combine,
        )


def run_distributed_q5(mesh, data: Q5Data, *, budget=None, task_id: int = 0,
                       manage_task: bool = True) -> List[Q5Row]:
    """Governed distributed q5 over host data: partials via
    :func:`run_q5_partials`, then the host rollup."""
    per_channel = run_q5_partials(
        mesh,
        {n: _facts_of(data.channels[n]) for n in CHANNELS},
        date_sk=data.date_sk,
        date_days=data.date_days,
        n_dims=tuple(len(data.channels[n].dim_sk) for n in CHANNELS),
        lo=data.sales_date_lo,
        hi=data.sales_date_hi,
        budget=budget,
        task_id=task_id,
        manage_task=manage_task,
    )
    return q5_rollup(per_channel,
                     {n: data.channels[n].dim_id for n in CHANNELS})


def q5_host_channel_partials(facts: Dict[str, np.ndarray], n_dim: int,
                             date_sk: np.ndarray, date_days: np.ndarray,
                             lo: int, hi: int) -> _ChannelPartials:
    """Host (numpy) oracle for one channel's partial vectors — the same
    join/filter/segment-sum semantics as the device body, int64-exact.
    Bucket-local by construction: its working set is the rows it is given
    (how streamed q5 verifies per bucket without a global materialize)."""
    def member(date, dvalid):
        idx = np.clip(np.searchsorted(date_sk, date), 0, len(date_sk) - 1)
        hit = date_sk[idx] == date
        in_win = (date_days[idx] >= lo) & (date_days[idx] < hi)
        return dvalid & hit & in_win

    def seg(values, sk, ok, dtype=np.int64):
        acc = np.zeros(n_dim, dtype)
        np.add.at(acc, sk[ok].astype(np.int64) - 1, values[ok].astype(dtype))
        return acc

    s_ok = (facts["sales_sk_valid"] & (facts["sales_sk"] >= 1)
            & (facts["sales_sk"] <= n_dim)
            & member(facts["sales_date"], facts["sales_date_valid"]))
    r_ok = (facts["ret_sk_valid"] & (facts["ret_sk"] >= 1)
            & (facts["ret_sk"] <= n_dim)
            & member(facts["ret_date"], facts["ret_date_valid"]))
    sales = seg(facts["sales_price"], facts["sales_sk"], s_ok)
    profit_s = seg(facts["sales_profit"], facts["sales_sk"], s_ok)
    returns_ = seg(facts["ret_amt"], facts["ret_sk"], r_ok)
    loss = seg(facts["ret_loss"], facts["ret_sk"], r_ok)
    count = (seg(np.ones_like(facts["sales_sk"]), facts["sales_sk"], s_ok,
                 np.int32)
             + seg(np.ones_like(facts["ret_sk"]), facts["ret_sk"], r_ok,
                   np.int32))
    return _ChannelPartials(sales, returns_, profit_s - loss, count)
