"""NDS q5: the three-channel sales/returns rollup (BASELINE config 5).

TPC-DS q5 unions store, catalog and web channel activity over a 14-day
window, computing per-business-id sales, returns and profit, grouped by
ROLLUP(channel, id).  The TPU-native plan per channel:

1. **date dim join** (device): membership of each fact row's date_sk in the
   filtered date_dim window via searchsorted over the (tiny, replicated)
   dim — the broadcast-join analog of the Spark plan.
2. **null-key semantics**: fact rows with null dim/date foreign keys drop
   out of the inner joins, exactly as in SQL.
3. **partial aggregation** (device): masked ``segment_sum`` into dense
   per-dim-sk buckets — sales cents, return cents, profit cents, and a
   contributing-row count.  Money is decimal(7,2) as unscaled int64 cents;
   sums widen to decimal(17,2) which stays int64-exact (Spark's own sum
   widening keeps precision+10).
4. **exchange**: ``psum`` of the partial vectors over the data axis (the
   aggregation all-reduce — rows never need a shuffle because the dim
   space is dense and small, the degenerate broadcast-join case).
5. **rollup** (host, tiny): (channel, id) rows -> channel totals -> grand
   total, with the string business ids attached from the dim table.

Since round 6 the whole device side is ONE compiled plan
(:func:`q5_plan`, plans/ir.py): all six fact streams (3 channels x
sales/returns), their window semi-joins and segment aggregations trace
into a single jitted program, cached on (plan structure, dtype
signature, pow2 batch bucket) in the process-global plan cache — the
per-query ``_q5_step_cached`` lru (and its geometry-keying foot-gun: a
fresh jit wrapper leaked per call when a key component didn't normalize,
~3 MB RSS each, tools/soak.py) is gone.  The governed runner admits the
whole plan as one working set and SplitAndRetryOOM re-executes the fused
program on split halves — exact, because every aggregate is additive.

The pre-plan eager per-op path survives as :func:`q5_local_unfused`, the
bit-parity oracle tests/test_plans.py pins the fused program against.
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.models.tpcds import CHANNELS, Q5Data
from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.ir import Bin, Cast, band_all, col, lit

__all__ = [
    "Q5Row",
    "q5_local",
    "q5_local_unfused",
    "q5_plan",
    "make_distributed_q5",
    "run_distributed_q5",
    "run_q5_partials",
    "q5_rollup",
    "q5_host_channel_partials",
    "ChannelPartials",
    "add_partials",
]


class Q5Row(NamedTuple):
    """One result row: ROLLUP levels use None for grouped-out columns."""

    channel: object  # str | None
    id: object  # str | None
    sales: int  # cents
    returns_: int
    profit: int


class ChannelPartials(NamedTuple):
    """Per-dim-sk partial aggregates of one channel — ADDITIVE over any
    disjoint row partition (the invariant row splits and the streamed
    bucket pipeline rely on)."""

    sales: jnp.ndarray  # int64[n_dim]
    returns_: jnp.ndarray
    profit: jnp.ndarray
    count: jnp.ndarray  # int32[n_dim] contributing rows (sales+returns)


_ChannelPartials = ChannelPartials


def add_partials(
    a: Dict[str, ChannelPartials], b: Dict[str, ChannelPartials]
) -> Dict[str, ChannelPartials]:
    """Element-wise sum of per-channel partial dicts (the additivity every
    split/bucket combine relies on)."""
    return {name: ChannelPartials(*(x + y for x, y in zip(a[name], b[name])))
            for name in a}


# ------------------------------------------------------------------ the plan


@functools.lru_cache(maxsize=64)
def q5_plan(n_dims: Tuple[int, ...], lo: int, hi: int) -> ir.Plan:
    """The whole q5 device pipeline as ONE plan: per channel, the sales
    and returns streams each scan -> bounds/null filter -> date-window
    semi-join -> masked segment aggregation; profit and count derive in
    post over the psum'd partial vectors.

    Geometry scalars are normalized to python ints here (via
    ``plans.ir.lit``), so equal geometry always builds an EQUAL plan —
    one cache entry, never a leaked fresh program per call (the
    ``_q5_step_cached`` geometry-keying fix, pinned by
    test_plans.test_compiled_step_identity_same_geometry and
    test_lit_normalizes_numpy_scalars).
    """
    n_dims = tuple(int(n) for n in n_dims)
    dim = ir.Dim("date_dim", ("sk", "days"))
    sinks: list = []
    post: list = []
    outputs: list = []

    for name, n_dim in zip(CHANNELS, n_dims):
        for suffix, value_fields, aggs in (
            ("sales", ("price", "profit"),
             ((f"{name}_sales", col("price"), "int64"),
              (f"{name}_profit_s", col("profit"), "int64"),
              (f"{name}_count_s", lit(1), "int32"))),
            ("ret", ("amt", "loss"),
             ((f"{name}_returns", col("amt"), "int64"),
              (f"{name}_loss", col("loss"), "int64"),
              (f"{name}_count_r", lit(1), "int32"))),
        ):
            node: ir.Node = ir.Scan(
                f"{name}_{suffix}",
                ("sk", "sk_valid", "date", "date_valid") + value_fields)
            node = ir.Filter(node, band_all(
                col("sk_valid"),
                Bin("ge", col("sk"), lit(1)),
                Bin("le", col("sk"), lit(n_dim)),
            ))
            node = ir.SemiJoinWindow(
                node, dim, key=col("date"), key_valid=col("date_valid"),
                sk_field="sk", days_field="days", lo=lit(lo), hi=lit(hi))
            sinks.append(ir.SegmentAgg(
                node, key=Bin("sub", Cast(col("sk"), "int32"), lit(1)),
                num_segments=n_dim, aggs=aggs))
        post.append((f"{name}_profit",
                     Bin("sub", col(f"{name}_profit_s"),
                         col(f"{name}_loss"))))
        post.append((f"{name}_count",
                     Bin("add", col(f"{name}_count_s"),
                         col(f"{name}_count_r"))))
        outputs.extend([f"{name}_sales", f"{name}_returns",
                        f"{name}_profit", f"{name}_count"])
    return ir.Plan("q5", tuple(sinks), tuple(post), tuple(outputs))


def _q5_tables(batch: Dict[str, Dict[str, np.ndarray]],
               date_sk: np.ndarray, date_days: np.ndarray):
    """The plan's input tables from a per-channel fact-array batch (the
    ``_facts_of`` field names)."""
    tables = {"date_dim": {"sk": np.asarray(date_sk),
                           "days": np.asarray(date_days)}}
    for name, facts in batch.items():
        tables[f"{name}_sales"] = {
            "sk": facts["sales_sk"], "sk_valid": facts["sales_sk_valid"],
            "date": facts["sales_date"],
            "date_valid": facts["sales_date_valid"],
            "price": facts["sales_price"], "profit": facts["sales_profit"],
        }
        tables[f"{name}_ret"] = {
            "sk": facts["ret_sk"], "sk_valid": facts["ret_sk_valid"],
            "date": facts["ret_date"], "date_valid": facts["ret_date_valid"],
            "amt": facts["ret_amt"], "loss": facts["ret_loss"],
        }
    return tables


def _partials_of(outputs: Dict[str, np.ndarray]) -> Dict[str, _ChannelPartials]:
    return {name: _ChannelPartials(
        outputs[f"{name}_sales"], outputs[f"{name}_returns"],
        outputs[f"{name}_profit"], outputs[f"{name}_count"])
        for name in CHANNELS}


# ------------------------------------------------------- unfused oracle path


def _window_member(date, date_valid, dim_sk, dim_days, lo, hi):
    """Inner-join membership of fact date_sk in the filtered date dim."""
    idx = jnp.clip(jnp.searchsorted(dim_sk, date), 0, dim_sk.shape[0] - 1)
    hit = dim_sk[idx] == date
    in_win = (dim_days[idx] >= lo) & (dim_days[idx] < hi)
    return date_valid & hit & in_win


def _masked_segment(values, sk, ok, n_dim, dtype=jnp.int64):
    """segment_sum of values into 1-based sk buckets, masked rows dropped."""
    bucket = jnp.where(ok, sk.astype(jnp.int32) - 1, n_dim)
    return jax.ops.segment_sum(
        jnp.where(ok, values, 0).astype(dtype), bucket, num_segments=n_dim + 1
    )[:-1]


def _channel_partials(ch, n_dim, dim_sk, dim_days, lo, hi) -> _ChannelPartials:
    """One shard's partial aggregates for one channel, per-op eager form.

    ``ch`` is a dict of this channel's fact arrays (see models/tpcds.py
    ChannelTables field names).  This is the pre-plan path, kept as the
    fused program's bit-parity oracle.
    """
    s_ok = ch["sales_sk_valid"] & (ch["sales_sk"] >= 1) & (
        ch["sales_sk"] <= n_dim
    ) & _window_member(ch["sales_date"], ch["sales_date_valid"],
                       dim_sk, dim_days, lo, hi)
    r_ok = ch["ret_sk_valid"] & (ch["ret_sk"] >= 1) & (
        ch["ret_sk"] <= n_dim
    ) & _window_member(ch["ret_date"], ch["ret_date_valid"],
                       dim_sk, dim_days, lo, hi)

    sales = _masked_segment(ch["sales_price"], ch["sales_sk"], s_ok, n_dim)
    profit_s = _masked_segment(ch["sales_profit"], ch["sales_sk"], s_ok, n_dim)
    returns_ = _masked_segment(ch["ret_amt"], ch["ret_sk"], r_ok, n_dim)
    loss = _masked_segment(ch["ret_loss"], ch["ret_sk"], r_ok, n_dim)
    count = (
        # analyze: ignore[governed-allocation] - per-op ORACLE path: since
        # the plan port this body runs only eagerly under q5_local_unfused,
        # the bit-parity reference the fused (governed) program is checked
        # against in tests; the ones masks are fact-row-sized, test-scoped
        _masked_segment(jnp.ones_like(ch["sales_sk"]), ch["sales_sk"],
                        s_ok, n_dim, jnp.int32)
        # analyze: ignore[governed-allocation] - same oracle-path rationale
        + _masked_segment(jnp.ones_like(ch["ret_sk"]), ch["ret_sk"],
                          r_ok, n_dim, jnp.int32)
    )
    return _ChannelPartials(sales, returns_, profit_s - loss, count)


def _facts_of(ch_tables) -> Dict[str, np.ndarray]:
    return {
        "sales_sk": ch_tables.sales_sk,
        "sales_sk_valid": ch_tables.sales_sk_valid,
        "sales_date": ch_tables.sales_date,
        "sales_date_valid": ch_tables.sales_date_valid,
        "sales_price": ch_tables.sales_price,
        "sales_profit": ch_tables.sales_profit,
        "ret_sk": ch_tables.ret_sk,
        "ret_sk_valid": ch_tables.ret_sk_valid,
        "ret_date": ch_tables.ret_date,
        "ret_date_valid": ch_tables.ret_date_valid,
        "ret_amt": ch_tables.ret_amt,
        "ret_loss": ch_tables.ret_loss,
    }


def q5_local_unfused(data: Q5Data) -> List[Q5Row]:
    """Per-op eager q5 (the pre-plan shape): one device dispatch per op,
    partials per channel, host rollup.  The plan path's oracle."""
    dim_sk = jnp.asarray(data.date_sk)
    dim_days = jnp.asarray(data.date_days)
    per_channel = {}
    for name in CHANNELS:
        ch = data.channels[name]
        parts = _channel_partials(
            {k: jnp.asarray(v) for k, v in _facts_of(ch).items()},
            len(ch.dim_sk), dim_sk, dim_days,
            data.sales_date_lo, data.sales_date_hi,
        )
        per_channel[name] = jax.tree.map(np.asarray, parts)
    return q5_rollup(per_channel,
                     {n: data.channels[n].dim_id for n in CHANNELS})


def q5_local(data: Q5Data) -> List[Q5Row]:
    """Single-chip q5 through the compiled plan: the whole six-stream
    pipeline is ONE jitted program (cached across calls on the pow2
    bucket lattice), then the host rollup."""
    from spark_rapids_jni_tpu.plans.runtime import execute_plan

    n_dims = tuple(len(data.channels[n].dim_sk) for n in CHANNELS)
    plan = q5_plan(n_dims, data.sales_date_lo, data.sales_date_hi)
    tables = _q5_tables({n: _facts_of(data.channels[n]) for n in CHANNELS},
                        data.date_sk, data.date_days)
    outputs = execute_plan(None, plan, tables)
    return q5_rollup(_partials_of(outputs),
                     {n: data.channels[n].dim_id for n in CHANNELS})


def q5_rollup(per_channel: Dict[str, _ChannelPartials],
              dim_ids: Dict[str, List[str]]) -> List[Q5Row]:
    """ROLLUP(channel, id) formatting: leaf rows, channel totals, grand
    total — ordered like the SQL output (channel, id, nulls last).
    ``dim_ids`` maps channel -> business-id strings (dim_sk order)."""
    rows: List[Q5Row] = []
    g_sales = g_ret = g_prof = 0
    for name in CHANNELS:
        p = per_channel[name]
        ids = dim_ids[name]
        c_sales = c_ret = c_prof = 0
        leaf: List[Q5Row] = []
        for i in range(len(ids)):
            if int(p.count[i]) == 0:
                continue  # group absent from the filtered join
            s, r, pr = int(p.sales[i]), int(p.returns_[i]), int(p.profit[i])
            leaf.append(Q5Row(name, ids[i], s, r, pr))
            c_sales += s
            c_ret += r
            c_prof += pr
        rows.extend(sorted(leaf, key=lambda q: q.id))
        rows.append(Q5Row(name, None, c_sales, c_ret, c_prof))
        g_sales += c_sales
        g_ret += c_ret
        g_prof += c_prof
    rows.append(Q5Row(None, None, g_sales, g_ret, g_prof))
    return rows


# ------------------------------------------------------------- distributed --


def make_distributed_q5(mesh, data: Q5Data):
    """Compiled distributed q5 plan over ``mesh``'s data axis.

    Returns the :class:`plans.cache.CompiledPlan` for ``data``'s geometry
    and batch bucket — facts sharded over DATA_AXIS, the date dim
    replicated, partial vectors psum'd.  Same-geometry data returns the
    IDENTICAL cached object (plan-cache identity; the leak-proof
    replacement for the old per-module lru step cache) with O(1) host
    work on a hit: the cache key derives from lengths and dtypes alone,
    never a padded copy of the dataset.
    """
    from spark_rapids_jni_tpu.plans.runtime import compiled_plan_for

    n_dims = tuple(len(data.channels[n].dim_sk) for n in CHANNELS)
    plan = q5_plan(n_dims, data.sales_date_lo, data.sales_date_hi)
    tables = _q5_tables({n: _facts_of(data.channels[n]) for n in CHANNELS},
                        data.date_sk, data.date_days)
    return compiled_plan_for(plan, mesh, tables)


def run_q5_partials(
    mesh,
    batch: Dict[str, Dict[str, np.ndarray]],
    *,
    date_sk: np.ndarray,
    date_days: np.ndarray,
    n_dims: Tuple[int, ...],
    lo: int,
    hi: int,
    budget=None,
    task_id: int = 0,
    manage_task: bool = True,
) -> Dict[str, _ChannelPartials]:
    """Governed distributed q5 PARTIALS over a host fact batch.

    ``batch`` maps channel -> fact-array dict (the _facts_of field names).
    The whole pipeline is ONE compiled plan under ONE governed bracket:
    one admission for the fused working set, RetryOOM re-runs the fused
    program, SplitAndRetryOOM halves every fact stream and re-executes
    the fused program per half (exact — all aggregates are additive),
    and one flight-recorder task spans the plan.  Every caller with one
    dim geometry and batch bucket — in-memory q5, every bucket of
    streamed q5 — reuses ONE cached program.
    """
    from spark_rapids_jni_tpu.plans.runtime import run_governed_plan

    plan = q5_plan(tuple(n_dims), lo, hi)
    tables = _q5_tables(batch, date_sk, date_days)
    outputs = run_governed_plan(
        mesh, plan, tables,
        budget=budget, task_id=task_id, manage_task=manage_task,
    )
    return _partials_of(outputs)


def run_distributed_q5(mesh, data: Q5Data, *, budget=None, task_id: int = 0,
                       manage_task: bool = True) -> List[Q5Row]:
    """Governed distributed q5 over host data: fused partials via
    :func:`run_q5_partials`, then the host rollup."""
    per_channel = run_q5_partials(
        mesh,
        {n: _facts_of(data.channels[n]) for n in CHANNELS},
        date_sk=data.date_sk,
        date_days=data.date_days,
        n_dims=tuple(len(data.channels[n].dim_sk) for n in CHANNELS),
        lo=data.sales_date_lo,
        hi=data.sales_date_hi,
        budget=budget,
        task_id=task_id,
        manage_task=manage_task,
    )
    return q5_rollup(per_channel,
                     {n: data.channels[n].dim_id for n in CHANNELS})


def q5_host_channel_partials(facts: Dict[str, np.ndarray], n_dim: int,
                             date_sk: np.ndarray, date_days: np.ndarray,
                             lo: int, hi: int) -> _ChannelPartials:
    """Host (numpy) oracle for one channel's partial vectors — the same
    join/filter/segment-sum semantics as the device body, int64-exact.
    Bucket-local by construction: its working set is the rows it is given
    (how streamed q5 verifies per bucket without a global materialize)."""
    def member(date, dvalid):
        idx = np.clip(np.searchsorted(date_sk, date), 0, len(date_sk) - 1)
        hit = date_sk[idx] == date
        in_win = (date_days[idx] >= lo) & (date_days[idx] < hi)
        return dvalid & hit & in_win

    def seg(values, sk, ok, dtype=np.int64):
        acc = np.zeros(n_dim, dtype)
        np.add.at(acc, sk[ok].astype(np.int64) - 1, values[ok].astype(dtype))
        return acc

    s_ok = (facts["sales_sk_valid"] & (facts["sales_sk"] >= 1)
            & (facts["sales_sk"] <= n_dim)
            & member(facts["sales_date"], facts["sales_date_valid"]))
    r_ok = (facts["ret_sk_valid"] & (facts["ret_sk"] >= 1)
            & (facts["ret_sk"] <= n_dim)
            & member(facts["ret_date"], facts["ret_date_valid"]))
    sales = seg(facts["sales_price"], facts["sales_sk"], s_ok)
    profit_s = seg(facts["sales_profit"], facts["sales_sk"], s_ok)
    returns_ = seg(facts["ret_amt"], facts["ret_sk"], r_ok)
    loss = seg(facts["ret_loss"], facts["ret_sk"], r_ok)
    count = (seg(np.ones_like(facts["sales_sk"]), facts["sales_sk"], s_ok,
                 np.int32)
             + seg(np.ones_like(facts["ret_sk"]), facts["ret_sk"], r_ok,
                   np.int32))
    return _ChannelPartials(sales, returns_, profit_s - loss, count)
