"""NDS end-to-end harness: governed q5 + q97 (+ q3) over TPC-DS-shaped data.

BASELINE config 5 is "NDS TPC-DS q5+q97 end-to-end"; this CLI is the
framework-native harness for it: generate tables at a scale factor, run
the queries distributed + governed (every launch admitted through the
memory arbiter), verify against host oracles, and report wall-clock.
q3 (star join + grouped agg) rides along as the third query pattern.

    python -m spark_rapids_jni_tpu.models.nds_harness --sf 0.1 --ndev 8

Prints one JSON line: per-query wall-clock, rows processed, verification
status.  On a single-device platform it builds a virtual mesh over the
available devices (ndev capped to the device count).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _q97_tables(sf: float, seed: int):
    from spark_rapids_jni_tpu.models.tpcds import generate_q97_tables

    return generate_q97_tables(sf, seed)


def q97_parquet_chunks(input_dir: str, n_splits: int):
    """Stream the q97 fact pair from parquet as ``(side, cust, item)``
    chunks, ONE ROW GROUP AT A TIME — the composition of the footer
    planner with the out-of-core shuffle.

    Every file is cut into footer-planned byte-range splits (each row
    group belongs to exactly one split, so iterating every split sees
    each row exactly once); the thrift footer filter (io/parquet_footer.py
    midpoint rule) decides which row groups each split reads, the schema
    prune limits decoding to the two join keys (money columns never
    materialize — NativeParquetJni.cpp:584 filter_groups feeding the
    columnar reader), and host memory is bounded by one row group.
    NULL keys are excluded (q97_host_oracle semantics) — this generator
    is the single owner of that filter for both --input modes.
    """
    import os

    import numpy as np

    from spark_rapids_jni_tpu.io import (
        StructElement,
        ValueElement,
        iter_split_batches,
        plan_byte_splits,
    )

    for name, prefix, side in (("store_sales", "ss", "store"),
                               ("catalog_sales", "cs", "catalog")):
        path = os.path.join(input_dir, f"{name}.parquet")
        schema = (StructElement.builder()
                  .add_child(f"{prefix}_customer_sk", ValueElement())
                  .add_child(f"{prefix}_item_sk", ValueElement())
                  .build())
        for off, length in plan_byte_splits(path, n_splits):
            for batch in iter_split_batches(path, off, length, schema,
                                            as_numpy=True):
                cust, cust_valid = batch[f"{prefix}_customer_sk"]
                item, item_valid = batch[f"{prefix}_item_sk"]
                cust = np.asarray(cust)
                item = np.asarray(item)
                keep = cust_valid
                if item_valid is not None:
                    keep = item_valid if keep is None else keep & item_valid
                if keep is not None:
                    cust, item = cust[keep], item[keep]
                yield (side,
                       cust.astype(np.int32, copy=False),
                       item.astype(np.int32, copy=False))


def _q97_tables_from_parquet(input_dir: str, n_splits: int):
    """Materialize the q97 fact pair from parquet (the in-memory --input
    mode): a per-side concatenate over :func:`q97_parquet_chunks`, so the
    footer planning / pruning / NULL-key semantics have one owner."""
    import numpy as np

    parts = {"store": ([], []), "catalog": ([], [])}
    for side, cust, item in q97_parquet_chunks(input_dir, n_splits):
        parts[side][0].append(cust)
        parts[side][1].append(item)

    def cat(side):
        custs, items = parts[side]
        return (np.concatenate(custs) if custs else np.zeros(0, np.int32),
                np.concatenate(items) if items else np.zeros(0, np.int32))

    return cat("store"), cat("catalog")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="NDS q5+q97 (+q3) end-to-end harness")
    ap.add_argument("--sf", type=float, default=0.05)
    ap.add_argument("--ndev", type=int, default=0, help="0 = all devices")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--verify", action="store_true",
                    help="check results against host oracles (slow at big sf)")
    ap.add_argument("--input", default="",
                    help="read the q97 fact pair from parquet files in DIR "
                         "(tpcds.write_q97_parquet layout); each file is "
                         "split-planned through io/parquet_footer")
    ap.add_argument("--splits", type=int, default=2,
                    help="byte-range splits per parquet file (--input mode)")
    ap.add_argument("--stream-chunk-rows", type=int, default=0,
                    help="run q5+q97 out-of-core: facts flow in bounded "
                         "chunks through disk grace-hash buckets "
                         "(models/streaming.py); 0 = in-memory.  Generated "
                         "facts chunk at this many rows; with --input, q97 "
                         "chunks at parquet row-group granularity instead")
    ap.add_argument("--buckets", type=int, default=16,
                    help="key-space buckets for --stream-chunk-rows mode")
    args = ap.parse_args(argv)

    # join the process group BEFORE the backend is touched: on a multi-host
    # pod the harness must span every host's devices, not run per-host
    from spark_rapids_jni_tpu.parallel import initialize_multihost

    initialize_multihost()

    import jax

    from spark_rapids_jni_tpu.mem import BudgetedResource, MemoryGovernor
    from spark_rapids_jni_tpu.models import (
        generate_q3_data,
        generate_q5_data,
        q3_local,
        q5_local,
        run_distributed_q3,
        run_distributed_q5,
        run_distributed_q97,
    )
    from spark_rapids_jni_tpu.parallel import make_mesh, make_pod_mesh

    if args.ndev in (0, len(jax.devices())):
        mesh = make_pod_mesh(mp=1)  # DCN-aware layout over all devices
        ndev = len(jax.devices())
    else:  # explicit subset: single-host experimentation path
        ndev = min(args.ndev, len(jax.devices()))
        mesh = make_mesh((ndev, 1), devices=jax.devices()[:ndev])
    gov = MemoryGovernor.initialize()
    budget = BudgetedResource(gov, 8 << 30)
    out = {"sf": args.sf, "ndev": ndev, "queries": {}}
    if args.input:
        out["input"] = args.input
        out["splits_per_file"] = args.splits

    try:
        budget.reset_peak()
        if args.stream_chunk_rows > 0:
            import tempfile

            from spark_rapids_jni_tpu.models.streaming import (
                generate_q5_chunks,
                generate_q97_chunks,
                run_streaming_q5,
                run_streaming_q97,
            )

            # host-side bucket staging is governed through the arbiter's
            # CPU path, like the reference's is_for_cpu ladder; one budget
            # PER QUERY so each reported host peak is that query's own
            def host_budget():
                return BudgetedResource(gov, 4 << 30, is_cpu=True)

            t0 = time.perf_counter()
            with tempfile.TemporaryDirectory(prefix="nds_q5_shuffle_") as td:
                q5_rows, q5_ok, q5_stats = run_streaming_q5(
                    mesh,
                    generate_q5_chunks(args.sf, args.seed,
                                       args.stream_chunk_rows),
                    tmpdir=td, n_buckets=args.buckets, budget=budget,
                    host_budget=host_budget(), task_id=1,
                    verify=args.verify)
            q5_dt = time.perf_counter() - t0
            q5_rows_total = q5_stats["rows_in"]
            out["queries"]["q5"] = {
                "wall_s": round(q5_dt, 3),
                "fact_rows": q5_rows_total,
                "Mrows_per_s": round(q5_rows_total / q5_dt / 1e6, 2),
                "result_rows": len(q5_rows),
                "verified": q5_ok,
                "streamed": q5_stats,
                "peak_reserved_bytes": budget.reset_peak(),
            }
        else:
            data = generate_q5_data(sf=args.sf, seed=args.seed)
            q5_rows_total = sum(
                len(ch.sales_sk) + len(ch.ret_sk)
                for ch in data.channels.values())
            t0 = time.perf_counter()
            q5_rows = run_distributed_q5(mesh, data, budget=budget, task_id=1)
            q5_dt = time.perf_counter() - t0
            q5_ok = (q5_rows == q5_local(data)) if args.verify else None
            out["queries"]["q5"] = {
                "wall_s": round(q5_dt, 3),
                "fact_rows": q5_rows_total,
                "Mrows_per_s": round(q5_rows_total / q5_dt / 1e6, 2),
                "result_rows": len(q5_rows),
                "verified": q5_ok,
                "peak_reserved_bytes": budget.reset_peak(),
            }

        if args.stream_chunk_rows > 0:
            if args.input:
                # footer-planned parquet scan feeding the disk shuffle:
                # chunk = one surviving row group per byte-range split
                q97_chunks = q97_parquet_chunks(args.input, args.splits)
            else:
                q97_chunks = generate_q97_chunks(args.sf, args.seed,
                                                 args.stream_chunk_rows)
            t0 = time.perf_counter()
            with tempfile.TemporaryDirectory(prefix="nds_shuffle_") as td:
                counts, q97_ok, stats = run_streaming_q97(
                    mesh, q97_chunks,
                    tmpdir=td, n_buckets=args.buckets, budget=budget,
                    host_budget=host_budget(), task_id=2, verify=args.verify)
            q97_dt = time.perf_counter() - t0
            nq = stats["rows_in"]
            out["queries"]["q97"] = {
                "wall_s": round(q97_dt, 3),
                "fact_rows": nq,
                "Mrows_per_s": round(nq / q97_dt / 1e6, 2),
                "counts": list(counts),
                "verified": q97_ok,
                "streamed": stats,
                "peak_reserved_bytes": budget.reset_peak(),
            }
        else:
            if args.input:
                store, catalog = _q97_tables_from_parquet(args.input,
                                                          args.splits)
            else:
                store, catalog = _q97_tables(args.sf, args.seed)
            nq = len(store[0]) + len(catalog[0])
            t0 = time.perf_counter()
            q97 = run_distributed_q97(mesh, store, catalog, budget=budget,
                                      task_id=2)
            q97_dt = time.perf_counter() - t0
            q97_ok = None
            if args.verify:
                from spark_rapids_jni_tpu.models.q97 import q97_host_oracle

                q97_ok = (q97.store_only, q97.catalog_only,
                          q97.both) == q97_host_oracle(store, catalog)
            out["queries"]["q97"] = {
                "wall_s": round(q97_dt, 3),
                "fact_rows": nq,
                "Mrows_per_s": round(nq / q97_dt / 1e6, 2),
                "counts": [int(q97.store_only), int(q97.catalog_only),
                           int(q97.both)],
                "verified": q97_ok,
                "peak_reserved_bytes": budget.reset_peak(),
            }

        q3_data = generate_q3_data(sf=args.sf, seed=args.seed)
        n3 = len(q3_data.ss_item_sk)
        t0 = time.perf_counter()
        q3_rows = run_distributed_q3(mesh, q3_data, budget=budget, task_id=3)
        q3_dt = time.perf_counter() - t0
        q3_ok = (q3_rows == q3_local(q3_data)) if args.verify else None
        out["queries"]["q3"] = {
            "wall_s": round(q3_dt, 3),
            "fact_rows": n3,
            "Mrows_per_s": round(n3 / q3_dt / 1e6, 2),
            "result_rows": len(q3_rows),
            "verified": q3_ok,
            "peak_reserved_bytes": budget.reset_peak(),
        }
        out["total_wall_s"] = round(q5_dt + q97_dt + q3_dt, 3)
    finally:
        MemoryGovernor.shutdown()

    print(json.dumps(out))
    failed = any(q.get("verified") is False for q in out["queries"].values())
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
