"""Named-table version registry: the result cache's invalidation lever.

Query payloads in this repo name their input tables (``store_sales``,
``catalog`` ... — the scan-table names of every compiled plan).  The
result cache (plans/rcache.py) fingerprints inputs by CONTENT (a CRC per
column buffer), which makes stale serves structurally impossible — but
content digests alone cannot *reclaim* anything: when a client declares
"table T changed", every cached result computed over T's old content is
dead weight that only falls out by LRU.  This registry is the missing
declaration: a process-local monotonic version per table name.

- Fingerprints embed ``version_of(name)`` per dependency, so a
  :func:`bump` makes every older entry UNREACHABLE (keys can no longer
  be rebuilt) the instant it returns;
- registered listeners (the result cache) run synchronously inside
  ``bump``, so the bumped table's entries are also RECLAIMED — their
  bytes return to the budget before the next query admits;
- in cluster serving the supervisor owns bumps
  (``Supervisor.bump_table``) and broadcasts ``MSG_TABLE_BUMP`` so every
  executor's registry converges via :func:`advance_to` (versions only
  move forward; a late broadcast can never roll one back).

Unregistered names read as version 0 — a table nobody ever bumps is
simply a table whose cache entries live by content digest + LRU alone.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Tuple

from spark_rapids_jni_tpu.obs import flight as _flight

__all__ = ["version_of", "versions_of", "bump", "advance_to",
           "snapshot", "add_listener", "remove_listener",
           "reset_for_tests"]

_lock = threading.Lock()
_versions: Dict[str, int] = {}  # guarded-by: _lock
# bump listeners: fn(name, new_version), called OUTSIDE the registry
# lock (a listener that consults versions must not deadlock) but on the
# bumping thread, so bump() returning means invalidation already ran
_listeners: List[Callable[[str, int], None]] = []  # guarded-by: _lock


def version_of(name: str) -> int:
    """Current version of ``name`` (0 = never bumped)."""
    with _lock:
        return _versions.get(name, 0)


def versions_of(names) -> Tuple[Tuple[str, int], ...]:
    """(name, version) per name, input order — the dependency stamp a
    result-cache fingerprint embeds."""
    with _lock:
        return tuple((n, _versions.get(n, 0)) for n in names)


def _notify(name: str, version: int) -> None:
    with _lock:
        listeners = list(_listeners)
    for fn in listeners:
        fn(name, version)


def bump(name: str) -> int:
    """Advance ``name``'s version by one and run invalidation listeners;
    returns the new version.  After this returns, no lookup anywhere in
    this process can serve a result fingerprinted with the old version."""
    with _lock:
        v = _versions[name] = _versions.get(name, 0) + 1
    _flight.record(_flight.EV_RCACHE_INVALIDATE, -1,
                   detail=f"table:{name}:version:{v}", value=v)
    _notify(name, v)
    return v


def advance_to(name: str, version: int) -> int:
    """Converge ``name`` to at least ``version`` (cross-process bump
    broadcasts).  Monotonic: a stale broadcast is a no-op.  Listeners run
    only when the version actually moved."""
    with _lock:
        cur = _versions.get(name, 0)
        if version <= cur:
            return cur
        _versions[name] = version
    _flight.record(_flight.EV_RCACHE_INVALIDATE, -1,
                   detail=f"table:{name}:version:{version}:broadcast",
                   value=version)
    _notify(name, version)
    return version


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_versions)


def add_listener(fn: Callable[[str, int], None]) -> None:
    with _lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_listener(fn: Callable[[str, int], None]) -> None:
    with _lock:
        if fn in _listeners:
            _listeners.remove(fn)


def reset_for_tests() -> None:
    with _lock:
        _versions.clear()
        _listeners.clear()


_flight.register_telemetry_source("table_versions", snapshot)
