"""Named-table version registry: the result cache's invalidation lever.

Query payloads in this repo name their input tables (``store_sales``,
``catalog`` ... — the scan-table names of every compiled plan).  The
result cache (plans/rcache.py) fingerprints inputs by CONTENT (a CRC per
column buffer), which makes stale serves structurally impossible — but
content digests alone cannot *reclaim* anything: when a client declares
"table T changed", every cached result computed over T's old content is
dead weight that only falls out by LRU.  This registry is the missing
declaration: a process-local monotonic version per table name.

- Fingerprints embed ``version_of(name)`` per dependency, so a
  :func:`bump` makes every older entry UNREACHABLE (keys can no longer
  be rebuilt) the instant it returns;
- registered listeners (the result cache) run synchronously inside
  ``bump``, so the bumped table's entries are also RECLAIMED — their
  bytes return to the budget before the next query admits;
- in cluster serving the supervisor owns bumps
  (``Supervisor.bump_table``) and broadcasts ``MSG_TABLE_BUMP`` so every
  executor's registry converges via :func:`advance_to` (versions only
  move forward; a late broadcast can never roll one back).

Unregistered names read as version 0 — a table nobody ever bumps is
simply a table whose cache entries live by content digest + LRU alone.

Round 19 extends the registry with per-table STATISTICS recorded at
upload (:func:`record_stats` / :func:`observe_tables`): row counts and a
content fingerprint, versioned with the table.  These are the
cost-model seeds the plan optimizer (plans/optimizer.py) reorders joins
by — a dim table's row count decides which gather applies first, and
the fingerprint lets a reader tell whether stats describe the content
currently registered or a previous version.  Stats for a version other
than the current one are dropped on read (a bump makes stale stats
unreachable exactly like it makes cache entries unreachable).
"""

from __future__ import annotations

import threading
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_jni_tpu.obs import flight as _flight

__all__ = ["version_of", "versions_of", "bump", "advance_to",
           "snapshot", "add_listener", "remove_listener",
           "record_stats", "observe_tables", "stats_of",
           "stats_snapshot",
           "reset_for_tests"]

_lock = threading.Lock()
_versions: Dict[str, int] = {}  # guarded-by: _lock
# name -> {"rows": int, "fingerprint": int, "version": int} recorded at
# upload; read by the optimizer's join-reorder rule  # guarded-by: _lock
_stats: Dict[str, dict] = {}
# bump listeners: fn(name, new_version), called OUTSIDE the registry
# lock (a listener that consults versions must not deadlock) but on the
# bumping thread, so bump() returning means invalidation already ran
_listeners: List[Callable[[str, int], None]] = []  # guarded-by: _lock


def version_of(name: str) -> int:
    """Current version of ``name`` (0 = never bumped)."""
    with _lock:
        return _versions.get(name, 0)


def versions_of(names) -> Tuple[Tuple[str, int], ...]:
    """(name, version) per name, input order — the dependency stamp a
    result-cache fingerprint embeds."""
    with _lock:
        return tuple((n, _versions.get(n, 0)) for n in names)


def _notify(name: str, version: int) -> None:
    with _lock:
        listeners = list(_listeners)
    for fn in listeners:
        fn(name, version)


def bump(name: str) -> int:
    """Advance ``name``'s version by one and run invalidation listeners;
    returns the new version.  After this returns, no lookup anywhere in
    this process can serve a result fingerprinted with the old version."""
    with _lock:
        v = _versions[name] = _versions.get(name, 0) + 1
    _flight.record(_flight.EV_RCACHE_INVALIDATE, -1,
                   detail=f"table:{name}:version:{v}", value=v)
    _notify(name, v)
    return v


def advance_to(name: str, version: int) -> int:
    """Converge ``name`` to at least ``version`` (cross-process bump
    broadcasts).  Monotonic: a stale broadcast is a no-op.  Listeners run
    only when the version actually moved."""
    with _lock:
        cur = _versions.get(name, 0)
        if version <= cur:
            return cur
        _versions[name] = version
    _flight.record(_flight.EV_RCACHE_INVALIDATE, -1,
                   detail=f"table:{name}:version:{version}:broadcast",
                   value=version)
    _notify(name, version)
    return version


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_versions)


# --------------------------------------------------------------------------
# per-table statistics (round 19): the optimizer's cost-model seeds
# --------------------------------------------------------------------------


def record_stats(name: str, *, rows: int, fingerprint: int = 0) -> None:
    """Record ``name``'s row count + content fingerprint AT UPLOAD,
    stamped with the current version — the registry's answer to "how big
    is this table right now".  Idempotent for identical content."""
    with _lock:
        _stats[name] = {"rows": int(rows),
                        "fingerprint": int(fingerprint),
                        "version": _versions.get(name, 0)}


def observe_tables(tables: Dict[str, Dict[str, "object"]]) -> None:
    """Record stats for every table in a ``{name: {field: array}}``
    upload payload: rows from the first column, fingerprint a CRC over
    each column's (name, dtype, length) header — cheap enough to run per
    upload, stable across identical uploads, and sensitive to schema or
    cardinality drift (content CRCs stay the result cache's job)."""
    for name, fields in tables.items():
        if not fields:
            continue
        rows = len(next(iter(fields.values())))
        fp = 0
        for fname in sorted(fields):
            v = fields[fname]
            fp = zlib.crc32(
                f"{fname}:{getattr(v, 'dtype', '')}:{len(v)}".encode(),
                fp)
        record_stats(name, rows=rows, fingerprint=fp)


def stats_of(name: str) -> Optional[dict]:
    """The stats recorded for ``name``'s CURRENT version, or None when
    never recorded / recorded for an older version (a bump makes stale
    stats unreachable, like cache entries)."""
    with _lock:
        st = _stats.get(name)
        if st is None or st["version"] != _versions.get(name, 0):
            return None
        return dict(st)


def stats_snapshot() -> Dict[str, dict]:
    """Current-version stats per table (stale entries filtered) — the
    telemetry view and the optimizer's bulk read."""
    with _lock:
        return {n: dict(st) for n, st in _stats.items()
                if st["version"] == _versions.get(n, 0)}


def add_listener(fn: Callable[[str, int], None]) -> None:
    with _lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_listener(fn: Callable[[str, int], None]) -> None:
    with _lock:
        if fn in _listeners:
            _listeners.remove(fn)


def reset_for_tests() -> None:
    with _lock:
        _versions.clear()
        _listeners.clear()
        _stats.clear()


_flight.register_telemetry_source("table_versions", snapshot)
_flight.register_telemetry_source("table_stats", stats_snapshot)
