"""spark_rapids_jni_tpu: a TPU-native re-architecture of spark-rapids-jni.

The reference library (/root/reference, NVIDIA spark-rapids-jni) is the native
CUDA/C++/JNI support layer for the RAPIDS Accelerator for Apache Spark: Spark-exact
columnar compute kernels, a multi-tenant device-memory governance state machine, and
observability/chaos tooling.  This package provides the same capabilities designed
TPU-first: columns are Arrow-layout pytrees of JAX arrays resident in HBM, kernels are
vectorized XLA/Pallas programs (SIMD-over-lanes rather than SIMT), multi-chip scaling
uses `jax.sharding` meshes with ICI/DCN collectives, and the memory arbiter governs
batch admission into HBM rather than intercepting `malloc`.

Layer map (mirrors SURVEY.md §1, re-drawn for TPU):

    L5  Python public API    spark_rapids_jni_tpu.ops / .mem / .profiler
    L4  dispatch seam        ops.dispatch (fault injection + tracing hook point)
    L3  op library           vectorized jnp/Pallas kernels over Column pytrees
    L2  columnar data model  spark_rapids_jni_tpu.columnar (Arrow layout in HBM)
    L1  JAX/XLA runtime      jit, sharding, collectives, profiler
"""

import os

import jax

# 64-bit integer support is required framework-wide: xxhash64, decimal128 limb math,
# JCUDF row offsets and timestamp micros are all 64-bit.  TPUs execute 64-bit integer
# ops as pairs of 32-bit ops; this is the standard JAX switch for it.
if os.environ.get("SPARK_RAPIDS_TPU_NO_X64") != "1":  # escape hatch for embedders
    jax.config.update("jax_enable_x64", True)

from spark_rapids_jni_tpu.version import VERSION as __version__  # noqa: E402
from spark_rapids_jni_tpu.version import build_info  # noqa: E402

from spark_rapids_jni_tpu.columnar import (  # noqa: E402
    Column,
    Decimal128Column,
    StringColumn,
    DType,
)

__all__ = [
    "Column",
    "Decimal128Column",
    "StringColumn",
    "DType",
    "__version__",
]
